"""dasdur durability suite (marker `dur`, standalone:
ops/pytests.sh dur) — ISSUE 15.

Pins, in order of load-bearing-ness:
  * CRASH-POINT MATRIX: a seeded fault at EVERY new persist site
    (snapshot_write / snapshot_rename / wal_append / wal_fsync /
    restore_read) × recover via restore() × bio-suite answers
    bit-identical to the uncrashed run — on TensorDB AND the 8-way
    mesh; a WAL-site failure additionally proves commit atomicity
    (store at the pre-commit state, the SAME delta commits after);
  * torn-tail WAL truncation: a crash mid-append leaves a partial
    frame; restore truncates it at the last valid boundary and NEVER
    replays it;
  * corrupt-section fallback: a flipped byte in the newest generation
    is detected by the manifest CRC and restore falls back to the
    prior generation + ITS WAL — same answers, typed telemetry;
  * warm-bundle staleness: a bundle recorded at snapshot version v is
    discarded when WAL replay moved the store past v (the result-cache
    delta_version guard applied to persistence);
  * warm-restore: a restored replica answers with ZERO capacity
    retries (1 compiled program) where a cold replica pays the retry
    tier — the CapStore/planner-stats bundle honored;
  * restore -> commit -> restore round trip;
  * the disabled path is the identity: no WAL configured means
    `_apply_delta` byte-for-byte unchanged (class-level `_wal is
    None`, DeltaLog.append never called, no files written);
  * DL017 on the real tree and a mutated copy (fsync deleted from
    atomic_write -> the analyzer fires).
"""

import os
from pathlib import Path

import pytest

from das_tpu import fault, kernels
from das_tpu.analysis import run_analysis
from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.core.config import DasConfig
from das_tpu.core.exceptions import InjectedFault, SnapshotCorruptError
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.query.ast import And, Link, Node, Variable
from das_tpu.storage import checkpoint, durable
from das_tpu.storage.delta import IncrementalCommitMixin
from das_tpu.storage.tensor_db import TensorDB

pytestmark = pytest.mark.dur

REPO = Path(__file__).resolve().parent.parent

#: the five persist seams this PR added (subset of fault.FAULT_SITES —
#: pinned here so the crash matrix cannot silently shrink)
PERSIST_FAULT_SITES = (
    "snapshot_write", "snapshot_rename", "wal_append", "wal_fsync",
    "restore_read",
)


@pytest.fixture(autouse=True)
def _disarm(monkeypatch):
    """Injection off after every test; CapStore/XLA persistence off so
    warm-bundle pins are controlled by THIS suite only."""
    monkeypatch.setenv("DAS_TPU_XLA_CACHE", "0")
    yield
    fault.configure(None)


def _bio_data(**kw):
    base = dict(n_genes=30, n_processes=5, members_per_gene=3,
                n_interactions=30, n_evaluations=6)
    base.update(kw)
    data, _, _ = build_bio_atomspace(**base)
    return data


def _ast(gene: str):
    return And([
        Link("Member", [Node("Gene", gene), Variable("$3")], True),
        Link("Member", [Variable("$2"), Variable("$3")], True),
        Link("Interacts", [Node("Gene", gene), Variable("$2")], True),
    ])


def _three_var():
    return And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Variable("V1"), Variable("V2")], True),
    ])


def _answers(das, queries):
    return [das.query(q) for q in queries]


def _commit_interaction(das, db, i: int):
    """One WAL-logged commit: a fresh gene interacting with an existing
    one (terminals declared — the bio KB is built programmatically, so
    MeTTa needs the `(: ...)` declarations)."""
    g0 = db.get_all_nodes("Gene", names=True)[0]
    tx = das.open_transaction()
    tx.add(f'(: "DURGENE:{i}" Gene)')
    tx.add(f'(: "{g0}" Gene)')
    tx.add(f'(Interacts "DURGENE:{i}" "{g0}")')
    das.commit_transaction(tx)


def _make_backend(data, backend, config=None):
    config = config or DasConfig()
    if backend == "sharded":
        from das_tpu.parallel.sharded_db import ShardedDB

        return ShardedDB(data, config)
    return TensorDB(data, config)


# -- the tentpole pin: crash-point matrix --------------------------------


def _crash_matrix(tmp_path, backend, site, seed):
    """Baseline snapshot -> WAL commit -> injected crash at `site` ->
    recover -> bit-identical answers to the live (uncrashed) store."""
    root = str(tmp_path / "snap")
    data = _bio_data()
    db = _make_backend(data, backend)
    das = DistributedAtomSpace(database_name=f"zdur_{site}", db=db)
    queries = [_ast(g) for g in db.get_all_nodes("Gene", names=True)[:3]]
    durable.write_snapshot(db, root)
    _commit_interaction(das, db, 0)
    live = _answers(das, queries)
    assert any(live), "KB too sparse to prove anything"

    if site in ("snapshot_write", "snapshot_rename"):
        # crash DURING the next snapshot: the new generation never
        # publishes, the prior one + WAL still reconstructs head
        fault.configure(f"seed={seed};sites={site};every=1;max=100")
        with pytest.raises(InjectedFault):
            durable.write_snapshot(db, root)
        fault.configure(None)
        assert [n for n, _ in durable.list_generations(root)] == [1]
        # no stray temp dirs survive a crashed snapshot
        assert not [
            d for d in os.listdir(root) if not d.startswith("gen-")
        ]
    elif site in ("wal_append", "wal_fsync"):
        # crash DURING a commit's WAL append: the commit fails typed
        # PRE-swap (atomicity), the store stays at the pre-commit
        # state, and the SAME delta commits once the fault clears
        v0 = db.delta_version
        g1 = db.get_all_nodes("Gene", names=True)[1]
        tx = das.open_transaction()
        tx.add('(: "DURGENE:crash" Gene)')
        tx.add(f'(: "{g1}" Gene)')
        tx.add(f'(Interacts "DURGENE:crash" "{g1}")')
        fault.configure(f"seed={seed};sites={site};every=1;max=100")
        with pytest.raises(InjectedFault):
            das.commit_transaction(tx)
        assert db.delta_version == v0  # unbumped: stage-then-swap held
        assert _answers(das, queries) == live
        fault.configure(None)
        das._refresh()  # the SAME staged delta commits cleanly
        assert db.delta_version == v0 + 1
        live = _answers(das, queries)
    else:  # restore_read: a transient read flake recovers via retry
        fault.configure(f"seed={seed};sites={site};every=1;max=1")

    if backend == "sharded":
        from das_tpu.parallel.sharded_db import ShardedDB

        restored = ShardedDB.restore(root)
    else:
        restored = TensorDB.restore(root)
    fault.configure(None)
    das2 = DistributedAtomSpace(database_name=f"zdur_{site}_r", db=restored)
    assert _answers(das2, queries) == live  # bit-identical recovery
    assert restored.delta_version == db.delta_version


@pytest.mark.parametrize("site", PERSIST_FAULT_SITES)
def test_crash_matrix_tensor(tmp_path, site):
    _crash_matrix(tmp_path, "tensor", site, seed=11)


@pytest.mark.parametrize("site", PERSIST_FAULT_SITES)
def test_crash_matrix_sharded(tmp_path, site):
    _crash_matrix(tmp_path, "sharded", site, seed=13)


def test_persist_sites_declared_in_fault_registry():
    """The chaos sweep in test_zfault parametrizes over FAULT_SITES —
    the five persist seams must stay members so serving-level chaos
    covers them too."""
    for site in PERSIST_FAULT_SITES:
        assert site in fault.FAULT_SITES, site


# -- WAL mechanics -------------------------------------------------------


def test_torn_tail_wal_truncated_not_replayed(tmp_path):
    root = str(tmp_path / "snap")
    data = _bio_data()
    db = TensorDB(data, DasConfig())
    das = DistributedAtomSpace(database_name="zdur_torn", db=db)
    queries = [_ast(g) for g in db.get_all_nodes("Gene", names=True)[:3]]
    durable.write_snapshot(db, root)
    _commit_interaction(das, db, 0)
    live = _answers(das, queries)

    wal_path = os.path.join(
        durable.list_generations(root)[-1][1], durable.WAL_FILE
    )
    clean_size = os.path.getsize(wal_path)
    assert clean_size > 0
    # a crash mid-append: valid header claiming more payload than ever
    # hit the disk
    with open(wal_path, "ab") as f:
        f.write(durable._WAL_HEADER.pack(durable.WAL_MAGIC, 1 << 20, 0))
        f.write(b"torn payload that never finished")
    before = durable.DUR_STATS["torn_tail_truncations"]
    restored = TensorDB.restore(root)
    assert durable.DUR_STATS["torn_tail_truncations"] == before + 1
    assert os.path.getsize(wal_path) == clean_size  # cut, not replayed
    das2 = DistributedAtomSpace(database_name="zdur_torn_r", db=restored)
    assert _answers(das2, queries) == live
    # ...and the truncated log keeps appending cleanly
    _commit_interaction(das2, restored, 1)
    restored2 = TensorDB.restore(root)
    das3 = DistributedAtomSpace(database_name="zdur_torn_r2", db=restored2)
    assert _answers(das3, queries) == _answers(das2, queries)


def test_midfile_wal_corruption_is_typed_never_truncated(tmp_path):
    """Mid-file corruption is categorically different from a torn tail:
    a fully-present frame failing its CRC may have fsync-acknowledged
    records BEHIND it, so read_wal refuses to truncate and raises
    typed — durable data is never silently destroyed."""
    root = str(tmp_path / "snap")
    db = TensorDB(_bio_data(), DasConfig())
    das = DistributedAtomSpace(database_name="zdur_garbage", db=db)
    durable.write_snapshot(db, root)
    _commit_interaction(das, db, 0)
    _commit_interaction(das, db, 1)  # a second fsynced record follows
    wal_path = os.path.join(
        durable.list_generations(root)[-1][1], durable.WAL_FILE
    )
    size = os.path.getsize(wal_path)
    with open(wal_path, "r+b") as f:
        f.seek(durable._WAL_HEADER.size + 2)  # inside record 1's payload
        f.write(b"\xde\xad")
    with pytest.raises(SnapshotCorruptError):
        durable.read_wal(wal_path)
    assert os.path.getsize(wal_path) == size  # refused to truncate
    # ...and the failure surfaces typed from restore too
    with pytest.raises(SnapshotCorruptError):
        TensorDB.restore(root)


def test_wal_record_format_roundtrip(tmp_path):
    """Frame-level unit: append two records, read them back verified,
    fields intact (version, kind, atoms, symbol tail)."""
    from das_tpu.storage.atom_table import load_metta_text

    data = load_metta_text(
        "(: Concept Type)\n(: Inheritance Type)\n"
        '(: "a" Concept)\n(: "b" Concept)\n'
    )
    log = durable.DeltaLog(str(tmp_path / "wal.log"), data)
    load_metta_text('(Inheritance "a" "b")', data)
    log.append(data, 2)
    load_metta_text('(: "c" Concept)\n(Inheritance "c" "b")', data)
    log.append(data, 3, kind="full")
    records, torn = durable.read_wal(log.path)
    assert not torn and [r["v"] for r in records] == [2, 3]
    assert records[0]["kind"] == "delta" and records[1]["kind"] == "full"
    # terminals materialize into data.nodes on first USE (the parser's
    # EOF fixpoint), so record 0 carries "a"/"b" + the link; record 1
    # carries "c" + its link
    assert len(records[0]["links"]) == 1 and len(records[0]["nodes"]) == 2
    assert len(records[1]["nodes"]) == 1 and len(records[1]["links"]) == 1
    assert records[1]["symbols"]["terminal_hash"]


# -- generation verification ---------------------------------------------


def test_corrupt_section_falls_back_to_prior_generation(tmp_path):
    root = str(tmp_path / "snap")
    db = TensorDB(_bio_data(), DasConfig(snapshot_keep=4))
    das = DistributedAtomSpace(database_name="zdur_corrupt", db=db)
    queries = [_ast(g) for g in db.get_all_nodes("Gene", names=True)[:3]]
    durable.write_snapshot(db, root)          # gen 1
    _commit_interaction(das, db, 0)           # -> gen 1's WAL
    live = _answers(das, queries)
    gen2 = durable.write_snapshot(db, root)   # gen 2 (same head state)

    # flip bytes inside gen 2's records section
    target = os.path.join(gen2, checkpoint.RECORDS_FILE)
    blob = bytearray(Path(target).read_bytes())
    blob[100:110] = b"\x00" * 10
    Path(target).write_bytes(bytes(blob))

    before = durable.DUR_STATS["corrupt_generations"]
    restored = TensorDB.restore(root)
    assert durable.DUR_STATS["corrupt_generations"] == before + 1
    # gen 1 + its WAL reconstructs the exact same head
    das2 = DistributedAtomSpace(database_name="zdur_corrupt_r", db=restored)
    assert _answers(das2, queries) == live

    # every generation corrupt -> typed, never silent
    gen1 = durable.list_generations(root)[0][1]
    t1 = os.path.join(gen1, checkpoint.RECORDS_FILE)
    blob = bytearray(Path(t1).read_bytes())
    blob[50:60] = b"\xff" * 10
    Path(t1).write_bytes(bytes(blob))
    with pytest.raises(SnapshotCorruptError):
        TensorDB.restore(root)


def test_manifest_absent_is_torn_generation(tmp_path):
    root = str(tmp_path / "snap")
    db = TensorDB(_bio_data(), DasConfig())
    gen1 = durable.write_snapshot(db, root)
    gen2 = durable.write_snapshot(db, root)
    os.remove(os.path.join(gen2, durable.MANIFEST_FILE))
    _data, manifest, gen_dir = durable.newest_valid_generation(root)
    assert gen_dir == gen1 and manifest["generation"] == 1


def test_generation_pruning_bounds_history(tmp_path):
    root = str(tmp_path / "snap")
    db = TensorDB(_bio_data(n_genes=6, n_interactions=4), DasConfig(
        snapshot_keep=2
    ))
    for _ in range(4):
        durable.write_snapshot(db, root)
    assert [n for n, _ in durable.list_generations(root)] == [3, 4]


def test_backcompat_unverified_checkpoint_warns_and_loads(tmp_path):
    """A pre-dasdur checkpoint (no MANIFEST.json) still loads —
    warn-and-accept once — and the next save records the digests."""
    path = str(tmp_path / "old")
    data = _bio_data(n_genes=6, n_interactions=4)
    checkpoint.save(data, path)
    os.remove(os.path.join(path, durable.MANIFEST_FILE))  # pre-dasdur
    restored = checkpoint.load(path)
    assert restored.count_atoms() == data.count_atoms()
    assert path in checkpoint._UNVERIFIED_WARNED
    checkpoint.save(restored, path)  # upgrade: digests recorded
    assert os.path.exists(os.path.join(path, durable.MANIFEST_FILE))
    durable.verify_generation(path)  # now fully verifiable


# -- warm bundle ---------------------------------------------------------


def test_warm_bundle_stale_on_version_mismatch(tmp_path):
    """CapStore data recorded at snapshot version v must NOT apply when
    WAL replay moved the store past v — the result-cache staleness
    guard applied to persistence."""
    from das_tpu.query.fused import apply_warm_state, get_executor

    root = str(tmp_path / "snap")
    db = TensorDB(_bio_data(), DasConfig())
    das = DistributedAtomSpace(database_name="zdur_stale", db=db)
    # learn something bundle-worthy, then snapshot
    das.query(_three_var())
    ex = get_executor(db)
    ex._cap_store._data["sentinel"] = [[1], [2]]
    durable.write_snapshot(db, root)
    _commit_interaction(das, db, 0)  # WAL moves head past the snapshot

    restored = TensorDB.restore(root)
    rex = get_executor(restored)
    assert "sentinel" not in rex._cap_store._data  # stale: discarded
    assert restored.delta_version == db.delta_version

    # the pure-function contract both ways
    state = {"delta_version": restored.delta_version + 1, "caps": {}}
    assert apply_warm_state(restored, state) is False
    state = {"delta_version": restored.delta_version,
             "caps": {"_cap_store": {"k": [[1], [2]]}}, "counts": []}
    assert apply_warm_state(restored, state) is True
    assert rex._cap_store._data["k"] == [[1], [2]]


def test_warm_bundle_applies_at_matching_version(tmp_path):
    """No commits after the snapshot: the bundle applies — CapStore
    data, count-cache entries and planner statistics all inherited."""
    from das_tpu.planner.stats import estimator_for
    from das_tpu.query.fused import get_executor

    root = str(tmp_path / "snap")
    db = TensorDB(_bio_data(), DasConfig())
    das = DistributedAtomSpace(database_name="zdur_warm", db=db)
    queries = [_ast(g) for g in db.get_all_nodes("Gene", names=True)[:2]]
    baseline = _answers(das, queries)
    # populate planner statistics + the count cache through real use
    from das_tpu.query import compiler

    das.query(_three_var())
    est = estimator_for(db)
    assert est is not None
    ex = get_executor(db)
    n_counts = ex.count_batch(
        [compiler.plan_query(db, q) for q in queries]
    )
    assert all(n is not None for n in n_counts)
    durable.write_snapshot(db, root)

    restored = TensorDB.restore(root)
    rex = get_executor(restored)
    rest = estimator_for(restored)
    # planner stats arrived without running anything
    assert rest._rows == est._rows and rest._distinct == est._distinct
    # count-cache entries answer with zero device work
    kernels.reset_dispatch_counts()
    plans = [compiler.plan_query(restored, q) for q in queries]
    assert rex.count_batch(plans) == n_counts
    assert kernels.DISPATCH_COUNTS["count"] == 0
    assert kernels.DISPATCH_COUNTS["count_kernel"] == 0
    das2 = DistributedAtomSpace(database_name="zdur_warm_r", db=restored)
    assert _answers(das2, queries) == baseline


def test_warm_restore_zero_capacity_retries(tmp_path):
    """The acceptance pin: a restored replica settles the fan-out query
    in ONE compiled program (0 capacity retries — the bundle's learned
    caps honored) where a cold replica without the bundle pays the
    retry tier (>= 2 programs).  Planner OFF so the greedy seed is the
    thing the bundle rescues."""
    root = str(tmp_path / "snap")
    data, _, _ = build_bio_atomspace(
        n_genes=32, n_processes=100, members_per_gene=50,
        n_interactions=0, seed=3,
    )
    cfg = DasConfig(use_planner="off")
    db = TensorDB(data, cfg)
    das = DistributedAtomSpace(database_name="zdur_caps", db=db)
    proc = db.get_all_nodes("BiologicalProcess", names=True)[0]
    q = And([
        Link("Member", [Variable("G"), Node("BiologicalProcess", proc)],
             True),
        Link("Member", [Variable("G"), Variable("P2")], True),
    ])
    kernels.reset_dispatch_counts()
    answer = das.query(q)  # learns the capacity the greedy seed missed
    cold_programs = kernels.DISPATCH_COUNTS["fused"]
    assert cold_programs >= 2, kernels.DISPATCH_COUNTS
    durable.write_snapshot(db, root)

    restored = TensorDB.restore(root, DasConfig(use_planner="off"))
    das2 = DistributedAtomSpace(database_name="zdur_caps_r", db=restored)
    kernels.reset_dispatch_counts()
    assert das2.query(q) == answer
    assert kernels.DISPATCH_COUNTS["fused"] == 1, (
        "restored replica was expected to settle in round 0 on the "
        f"bundled caps; dispatches={kernels.DISPATCH_COUNTS}"
    )

    # control: a cold replica from the same records (no bundle) still
    # pays the tier — the bundle, not the snapshot, is what helped
    cold = TensorDB(checkpoint.load(
        durable.list_generations(root)[-1][1], _verified=True
    ), DasConfig(use_planner="off"))
    das3 = DistributedAtomSpace(database_name="zdur_caps_c", db=cold)
    kernels.reset_dispatch_counts()
    assert das3.query(q) == answer
    assert kernels.DISPATCH_COUNTS["fused"] >= 2


# -- round trip + disabled-path identity ---------------------------------


def test_restore_commit_restore_round_trip(tmp_path):
    root = str(tmp_path / "snap")
    db = TensorDB(_bio_data(), DasConfig())
    das = DistributedAtomSpace(database_name="zdur_rt", db=db)
    queries = [_ast(g) for g in db.get_all_nodes("Gene", names=True)[:3]]
    durable.write_snapshot(db, root)
    _commit_interaction(das, db, 0)

    r1 = TensorDB.restore(root)
    das1 = DistributedAtomSpace(database_name="zdur_rt1", db=r1)
    assert _answers(das1, queries) == _answers(das, queries)
    _commit_interaction(das1, r1, 1)  # commit on the RESTORED store
    live = _answers(das1, queries)

    r2 = TensorDB.restore(root)
    das2 = DistributedAtomSpace(database_name="zdur_rt2", db=r2)
    assert _answers(das2, queries) == live
    assert r2.delta_version == r1.delta_version


def test_disabled_path_is_identity(tmp_path, monkeypatch):
    """No WAL configured: `_wal` is the CLASS-level None (one attribute
    read on the commit hot path, no new allocations), DeltaLog.append
    is never entered, and no persist file appears anywhere."""
    assert IncrementalCommitMixin._wal is None
    assert IncrementalCommitMixin._snapshot_root is None
    db = TensorDB(_bio_data(n_genes=6, n_interactions=4), DasConfig())
    assert db._wal is IncrementalCommitMixin._wal  # class attr, no copy
    das = DistributedAtomSpace(database_name="zdur_off", db=db)

    def boom(*a, **k):  # pragma: no cover - the pin is that it never runs
        raise AssertionError("DeltaLog.append reached with no WAL")

    monkeypatch.setattr(durable.DeltaLog, "append", boom)
    before = dict(durable.DUR_STATS)
    _commit_interaction(das, db, 0)
    assert db._wal is None
    assert durable.snapshot_stats()["wal_records"] == before["wal_records"]


def test_obs_enabled_durability_spans_and_metrics(tmp_path):
    """The full snapshot→commit→restore cycle with the obs layer ON
    (the serving default under DAS_TPU_TRACE=1): spans/events/counters/
    histogram all record through their REAL APIs — a typo'd metric
    call must fail here, not in production (the live drive caught
    `.record` vs `.observe` exactly once; never again)."""
    from das_tpu import obs

    root = str(tmp_path / "snap")
    db = TensorDB(_bio_data(n_genes=6, n_interactions=4), DasConfig())
    das = DistributedAtomSpace(database_name="zdur_obs", db=db)
    obs.configure(enabled=True)
    try:
        obs.reset()
        durable.write_snapshot(db, root)
        _commit_interaction(das, db, 0)
        restored = TensorDB.restore(root)
        assert restored.delta_version == db.delta_version
        assert obs.metrics.COUNTERS["dur.snapshots"].value >= 1
        assert obs.metrics.COUNTERS["dur.wal_records"].value >= 1
        assert obs.metrics.COUNTERS["dur.recovery_replayed"].value >= 1
        assert obs.metrics.HISTOGRAMS["dur.restore_ms"].total >= 1
        names = {e[0] for e in obs.events()}
        assert {"dur.snapshot", "dur.restore", "dur.wal_append"} <= names
    finally:
        obs.configure(enabled=False)
        obs.reset()


def test_stats_surface_and_prometheus_gauges(tmp_path):
    from das_tpu.service.server import DasService, _Tenant

    root = str(tmp_path / "snap")
    db = TensorDB(_bio_data(n_genes=6, n_interactions=4), DasConfig())
    das = DistributedAtomSpace(database_name="zdur_stats", db=db)
    durable.write_snapshot(db, root)
    _commit_interaction(das, db, 0)
    TensorDB.restore(root)

    svc = DasService()
    tenant = _Tenant("t", das)
    svc.tenants["t"] = tenant
    stats = svc.coalescer_stats()
    dur = stats["durability"]
    for key in ("generation", "snapshots", "wal_records",
                "recovery_replayed", "torn_tail_truncations",
                "corrupt_generations", "last_restore_s"):
        assert key in dur, key
    assert dur["generation"] >= 1 and dur["wal_records"] >= 1
    assert dur["recovery_replayed"] >= 1
    assert dur["last_restore_s"] is not None
    text = svc.metrics_text()
    assert "durability_generation" in text
    assert "durability_wal_records" in text
    assert "durability_last_restore_s" in text


def test_snapshot_dir_config_auto_restore(tmp_path, monkeypatch):
    """DAS_TPU_SNAPSHOT_DIR end-to-end: a bare DistributedAtomSpace()
    over a populated root restores it; over an empty root it writes
    generation 1 and arms the WAL."""
    root = str(tmp_path / "snap")
    das = DistributedAtomSpace(
        backend="tensor", config=DasConfig(snapshot_dir=root),
    )
    # the API namespaces the root per database_name: one generation
    # lineage = one store (service tenants sharing DAS_TPU_SNAPSHOT_DIR
    # must not restore each other's atoms or interleave WALs)
    lineage = os.path.join(root, das.database_name)
    assert [n for n, _ in durable.list_generations(lineage)] == [1]
    assert not durable.list_generations(root)
    assert das.db._wal is not None
    das.load_metta_text(
        "(: Concept Type)\n(: Inheritance Type)\n"
        '(: "a" Concept)\n(: "m" Concept)\n(Inheritance "a" "m")'
    )
    q = And([Link("Inheritance",
                  [Variable("$x"), Node("Concept", "m")], True)])
    answer = das.query(q)

    das2 = DistributedAtomSpace(
        backend="tensor", config=DasConfig(snapshot_dir=root),
    )
    assert das2.db.count_atoms() == das.db.count_atoms()
    assert das2.query(q) == answer
    # env spelling reaches the same path
    monkeypatch.setenv("DAS_TPU_SNAPSHOT_DIR", root)
    assert DasConfig.from_env().snapshot_dir == root
    monkeypatch.setenv("DAS_TPU_WAL", "off")
    assert not durable.wal_enabled(DasConfig.from_env())


def test_attach_refuses_foreign_root_writes_fresh_generation(tmp_path):
    """Arming a DIFFERENT store's WAL would silently drop (or brick)
    its commits at replay: attach() reuses a populated lineage only
    when the newest generation provably describes the live store;
    anything else gets a fresh generation."""
    root = str(tmp_path / "snap")
    db_a = TensorDB(_bio_data(n_genes=6, n_interactions=4), DasConfig())
    durable.write_snapshot(db_a, root)
    db_b = TensorDB(_bio_data(n_genes=9, n_interactions=6), DasConfig())
    gen_dir = durable.attach(db_b, root)
    assert gen_dir.endswith("gen-000002")  # fresh, not A's lineage
    das_b = DistributedAtomSpace(database_name="zdur_foreign", db=db_b)
    _commit_interaction(das_b, db_b, 0)
    restored = TensorDB.restore(root)
    assert restored.count_atoms() == db_b.count_atoms()  # B, not A
    # ...while re-attaching a store the newest generation already
    # describes (a fresh snapshot of db_b's head) REUSES it
    head_gen = durable.write_snapshot(db_b, root)
    db_c = TensorDB(db_b.data, DasConfig())
    db_c.delta_version = db_b.delta_version
    assert durable.attach(db_c, root) == head_gen
    assert durable.list_generations(root)[-1][1] == head_gen


def test_attach_refuses_generation_with_nonempty_wal(tmp_path):
    """A matched generation whose WAL already holds records is a
    lineage whose head moved PAST the snapshot: re-arming it would let
    a second writer append duplicate delta_versions that replay dedups
    away (silently dropped fsynced commits) — attach must take a fresh
    generation instead."""
    root = str(tmp_path / "snap")
    db = TensorDB(_bio_data(n_genes=6, n_interactions=4), DasConfig())
    das = DistributedAtomSpace(database_name="zdur_refuse", db=db)
    gen1 = durable.write_snapshot(db, root)
    _commit_interaction(das, db, 0)  # gen1's WAL now has a record

    # a second process rebuilds the SNAPSHOT-state store (version and
    # content both match gen1's manifest) — but gen1's WAL is not empty
    data2 = checkpoint.load(gen1, _verified=True)
    db2 = TensorDB(data2, DasConfig())
    gen = durable.attach(db2, root)
    assert gen != gen1  # fresh generation, never the moved-on lineage


def test_generational_checkpoint_load_includes_wal_commits(tmp_path):
    """checkpoint.load on a generational root must not silently serve
    the snapshot WITHOUT the fsync-acknowledged WAL commits behind it
    (DAS_TPU_CHECKPOINT pointed at a lineage dir is a documented
    spelling)."""
    root = str(tmp_path / "snap")
    db = TensorDB(_bio_data(n_genes=6, n_interactions=4), DasConfig())
    das = DistributedAtomSpace(database_name="zdur_ckload", db=db)
    durable.write_snapshot(db, root)
    _commit_interaction(das, db, 0)

    data = checkpoint.load(root)
    assert data.count_atoms() == db.data.count_atoms()  # WAL included
    das2 = DistributedAtomSpace(
        backend="tensor", config=DasConfig(checkpoint_path=root),
    )
    assert das2.count_atoms() == db.count_atoms()


def test_flat_checkpoint_missing_optional_section_still_loads(tmp_path):
    """The pre-dasdur contract holds under verification: deleting
    indexes.npz from a flat checkpoint forces the re-finalize slow
    path, never a corruption error — only PRESENT bytes must match."""
    path = str(tmp_path / "flat")
    data = _bio_data(n_genes=6, n_interactions=4)
    checkpoint.save(data, path)
    os.remove(os.path.join(path, checkpoint.INDEXES_FILE))
    restored = checkpoint.load(path)
    assert restored.count_atoms() == data.count_atoms()
    assert restored._fin is None  # re-finalize path, not a crash


# -- DL017 on the real tree ----------------------------------------------


def test_dl017_fires_on_fsyncless_atomic_write(tmp_path):
    """Mutated-copy regression (the DL004/DL015 idiom): delete the
    os.fsync from the REAL atomic_write — the analyzer must fire the
    fsync-before-rename pin."""
    src = (REPO / "das_tpu/storage/durable.py").read_text()
    needle = "            os.fsync(f.fileno())\n        fault.maybe_fail"
    assert needle in src, "durable.py atomic_write layout changed"
    mutated = tmp_path / "durable_mutated.py"
    mutated.write_text(src.replace(
        needle, "            pass\n        fault.maybe_fail", 1
    ))
    findings = run_analysis([mutated], rules=["DL017"], partial=True)
    assert any(
        "os.fsync" in f.message and "atomic_write" in f.message
        for f in findings
    ), "\n".join(f.render() for f in findings)
    # the committed module stays clean
    clean = run_analysis(
        [REPO / "das_tpu/storage/durable.py",
         REPO / "das_tpu/storage/checkpoint.py",
         REPO / "das_tpu/service/seed_checkpoint.py"],
        rules=["DL017"], partial=True,
    )
    assert clean == [], "\n".join(f.render() for f in clean)


def test_dl017_fires_on_bare_write_in_persist_scope(tmp_path):
    """A bare open(..., "wb") added to checkpoint.py must fail lint even
    though the module itself declares no registry — PERSIST_SCOPES
    covers it by path suffix."""
    scope_dir = tmp_path / "das_tpu" / "storage"
    scope_dir.mkdir(parents=True)
    (scope_dir / "durable.py").write_text(
        (REPO / "das_tpu/storage/durable.py").read_text()
    )
    bad = scope_dir / "checkpoint.py"
    bad.write_text(
        "import os\n"
        "def save(path, payload):\n"
        "    with open(path, 'wb') as f:\n"
        "        f.write(payload)\n"
    )
    findings = run_analysis(
        [scope_dir / "durable.py", bad], rules=["DL017"], partial=True
    )
    assert any(
        "bare write-mode open()" in f.message
        and f.path.endswith("checkpoint.py")
        for f in findings
    ), "\n".join(f.render() for f in findings)
