"""dasprof program ledger (ISSUE 14): compile/cost/memory telemetry,
byte-model calibration, the bench-history regression gate, and the
DL016 program-site registry discipline.

Pins, in one place (marker `prof`, standalone via `ops/pytests.sh
prof`):

  * DISABLED path is the identity: `instrument(...)` returns the jitted
    fn ITSELF (no wrapper objects), a served workload records nothing,
    and the analyzer's DL001/DL010 clean-tree run (test_zlint) covers
    the sync-free dispatch halves either way;
  * ledger lifecycle on both backends: one compile entry per program
    signature carrying wall seconds + cost_analysis (flops, bytes
    accessed) + memory_analysis byte columns, repeat calls of the same
    shape counted as ledger hits, answers bit-identical to the
    un-instrumented path;
  * the acceptance pin: the bio 3-var query under the coalescer yields
    a ledger entry with compile wall time + cost/memory analysis, and
    `explain(compile=True)` renders it by digest;
  * byte-model calibration sanity on the interpreter: a kernel-routed
    program records modeled_bytes > 0 and a finite positive
    budget_vs_actual_ratio (the CPU ratio is a sanity signal — the
    calibration CONTRACT is for TPU runs, ARCHITECTURE §15);
  * cold-start accounting: a persistent-XLA-cache-served compile is
    classified as a hit and excluded from cold_start_s;
  * scripts/bench_diff.py: the committed trajectory passes its own
    gate, a synthetically regressed headline exits nonzero, and the
    honesty rule (interpret records never gate device records) holds;
  * daslint DL016 — clean tree, bad/good fixtures, and a mutated-copy
    regression deleting the real build_fused instrument hook.

Compile-budget note: every query here reuses small animals-KB plan
shapes (the test_zpipeline idiom); the bio acceptance case runs ONE
3-var shape.
"""

import importlib.util
import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from das_tpu import obs
from das_tpu.api.atomspace import DistributedAtomSpace, QueryOutputFormat
from das_tpu.core.config import DasConfig
from das_tpu.models.animals import animals_metta
from das_tpu.obs import proflog
from das_tpu.query.ast import And, Link, Node, Or, Variable
from das_tpu.storage.atom_table import load_metta_text
from das_tpu.storage.tensor_db import TensorDB

pytestmark = pytest.mark.prof

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"


def _inherit_query(anchor="animal"):
    return And([
        Link("Inheritance", [Variable("$1"), Variable("$2")], True),
        Link("Inheritance", [Variable("$2"), Node("Concept", anchor)], True),
    ])


def _tensor_das(config=None):
    data = load_metta_text(animals_metta())
    db = TensorDB(data, config or DasConfig())
    return DistributedAtomSpace(database_name="zprof", db=db), db


@pytest.fixture
def ledger():
    """Ledger ON for the test body, clean before and after, OFF again
    on exit — the rest of the suite must keep running the identity
    fast path."""
    proflog.configure(enabled=True)
    proflog.reset()
    yield
    proflog.reset()
    proflog.configure(enabled=False)


def _bench_diff():
    spec = importlib.util.spec_from_file_location(
        "bench_diff", REPO / "scripts" / "bench_diff.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_diff"] = mod  # dataclass annotations need this
    spec.loader.exec_module(mod)
    return mod


# -- disabled path ---------------------------------------------------------


def test_disabled_instrument_is_identity():
    """The no-allocation contract: with the ledger off, instrument()
    hands back the very callable it was given — the serving path is
    structurally the pre-ledger path."""
    assert not proflog.enabled()

    def fn(x):
        return x

    assert proflog.instrument("fused", "deadbeef", fn) is fn


def test_disabled_workload_records_nothing():
    das, _db = _tensor_das()
    ok, ans = das.query_answer(_inherit_query())
    assert ok and ans.assignments
    snap = proflog.snapshot()
    assert snap["enabled"] is False
    assert snap["compiles"] == 0 and snap["entries"] == 0
    assert snap["launches"] == 0 and snap["calls"] == 0


# -- ledger lifecycle ------------------------------------------------------


def test_tensor_lifecycle_compile_then_hits(ledger):
    das, _db = _tensor_das()
    ok1, ans1 = das.query_answer(_inherit_query("animal"))
    # a DIFFERENT grounding of the same plan shape: same signature,
    # same compiled program — must be a ledger hit, not a compile
    ok2, _ans2 = das.query_answer(_inherit_query("mammal"))
    assert ok1 and ans1.assignments
    assert ok2 is not None  # empty answer is fine — the program still ran
    snap = proflog.snapshot()
    assert snap["compiles"] == 1, snap
    assert snap["calls"] >= 2 and snap["ledger_hits"] >= 1
    assert snap["hit_rate"] > 0
    (row,) = proflog.rows(site="fused")
    assert row["compiles"] == 1
    assert row["compile_s"] > 0
    assert row["first_compile_s"] == pytest.approx(row["compile_s"])
    # cost_analysis + memory_analysis columns (CPU backend provides
    # both; where a backend doesn't, the columns stay None — "where the
    # backend provides them")
    assert row["flops"] is not None and row["flops"] > 0
    assert row["bytes_accessed"] is not None
    assert row["peak_bytes"] is not None and row["peak_bytes"] > 0
    assert row["error"] is None


def test_answers_bit_identical_on_vs_off(ledger):
    das_on, _ = _tensor_das()
    _ok, on = das_on.query_answer(_inherit_query())
    proflog.configure(enabled=False)
    das_off, _ = _tensor_das()
    _ok, off = das_off.query_answer(_inherit_query())
    assert on.assignments == off.assignments


def test_sharded_lifecycle(ledger):
    from das_tpu.parallel.sharded_db import ShardedDB

    db = ShardedDB(
        load_metta_text(animals_metta()), DasConfig(backend="sharded")
    )
    das = DistributedAtomSpace(database_name="zprof-mesh", db=db)
    ok, ans = das.query_answer(_inherit_query())
    assert ok and ans.assignments
    rows = proflog.rows(site="sharded")
    assert rows and rows[0]["compiles"] == 1
    assert rows[0]["compile_s"] > 0 and rows[0]["flops"] is not None


def test_tree_site_records(ledger):
    das, _db = _tensor_das()
    q = Or([_inherit_query("animal"), _inherit_query("mammal")])
    ok, ans = das.query_answer(q)
    assert ok and ans.assignments
    rows = proflog.rows(site="fused_tree")
    assert rows and rows[0]["compiles"] >= 1
    assert rows[0]["peak_bytes"] is not None


def test_count_batch_site_records(ledger):
    from das_tpu.query import compiler
    from das_tpu.query.fused import get_executor

    das, db = _tensor_das()
    plans = [
        compiler.plan_query(db, _inherit_query(a))
        for a in ("animal", "mammal")
    ]
    counts = get_executor(db).count_batch(plans)
    assert all(c is not None for c in counts)
    rows = proflog.rows(site="count_batch")
    assert rows and rows[0]["compiles"] >= 1


def test_kernel_launch_notes(ledger, monkeypatch):
    monkeypatch.setenv("DAS_TPU_PALLAS", "on")
    das, _db = _tensor_das()
    ok, ans = das.query_answer(_inherit_query())
    assert ok and ans.assignments
    rows = proflog.rows(site="kernel")
    assert rows, "kernel-routed program must note its launches"
    assert all(r["kind"] in ("pallas", "discharge") for r in rows)
    assert sum(r["launches"] for r in rows) >= 1
    assert proflog.snapshot()["launches"] >= 1
    # trace wall is kept APART from compile seconds (honesty: tracing
    # is host cost, not XLA compile)
    assert all(r["compile_s"] == 0.0 for r in rows)


# -- byte-model calibration ------------------------------------------------


def test_budget_vs_actual_ratio_sanity(ledger, monkeypatch):
    """Interpreter-sanity pin for the §15 calibration contract: a
    kernel-routed program records the modeled combined footprint the
    route gate used and a finite positive ratio against the XLA
    allocation."""
    monkeypatch.setenv("DAS_TPU_PALLAS", "on")
    das, _db = _tensor_das()
    ok, _ans = das.query_answer(_inherit_query())
    assert ok
    (row,) = proflog.rows(site="fused")
    assert row["modeled_bytes"] and row["modeled_bytes"] > 0
    ratio = row["budget_vs_actual_ratio"]
    assert ratio is not None and 0 < ratio < 1e6
    snap = proflog.snapshot()
    assert snap["budget_vs_actual"].get("fused") == pytest.approx(
        ratio, rel=1e-6
    )


# -- acceptance: bio 3-var under the coalescer + explain(compile=True) -----


def test_bio_three_var_coalescer_and_explain_compile(ledger):
    from das_tpu.models.bio import build_bio_atomspace
    from das_tpu.service.coalesce import QueryCoalescer
    from das_tpu.service.server import _Tenant

    data, _genes, _procs = build_bio_atomspace(
        n_genes=64, n_processes=16, members_per_gene=5, n_interactions=128
    )
    db = TensorDB(data, DasConfig())
    das = DistributedAtomSpace(database_name="zprof-bio", db=db)
    q = And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Variable("V1"), Variable("V2")], True),
    ])
    coal = QueryCoalescer(max_batch=16)
    fut = coal.submit(_Tenant("zprof-bio", das), q, QueryOutputFormat.HANDLE)
    assert fut.result(timeout=300) is not None
    rows = proflog.rows(site="fused")
    assert rows, "the served 3-var query must land a ledger entry"
    row = rows[0]
    assert row["compile_s"] > 0 and row["flops"] is not None
    assert row["peak_bytes"] is not None
    # explain(compile=True) renders the SAME entry by digest
    out = das.explain(q, compile=True)
    comp = out["compile"]
    assert comp is not None and comp["enabled"] is True
    assert comp["rows"], out
    assert comp["rows"][0]["digest"] == comp["digest"]
    for col in ("site", "compiles", "compile_s", "flops",
                "bytes_accessed", "arg_bytes", "out_bytes", "temp_bytes",
                "peak_bytes", "budget_vs_actual_ratio"):
        assert col in comp["rows"][0]
    # compile=True implies execute: the actual block rides along
    assert out["actual"]["count"] is not None


def test_explain_compile_disabled_reports_enabled_false():
    das, _db = _tensor_das()
    das.query(_inherit_query())
    out = das.explain(_inherit_query(), compile=True)
    assert out["compile"]["enabled"] is False
    assert out["compile"]["rows"] == []


# -- cold-start / persistent XLA cache ------------------------------------


def test_persistent_cache_hit_excluded_from_cold_start(ledger, tmp_path):
    import jax

    try:
        from jax._src.compilation_cache import reset_cache
    except Exception:
        pytest.skip("jax compilation-cache reset API unavailable")

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min_t = jax.config.jax_persistent_cache_min_compile_time_secs
    prev_min_b = jax.config.jax_persistent_cache_min_entry_size_bytes
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # the persistent cache binds its directory at first use; earlier
    # tests in the process may have initialized it already (das_tpu
    # enables DAS_TPU_XLA_CACHE's default dir at import)
    reset_cache()
    try:
        das, _db = _tensor_das()
        ok, _ = das.query_answer(_inherit_query())
        assert ok
        first = proflog.snapshot()
        assert first["compiles"] == 1
        assert first["persistent_cache_hits"] == 0
        assert first["cold_start_s"] == pytest.approx(first["compile_s"])
        # a fresh process would reuse the persistent cache; simulate it
        # by dropping jax's in-memory caches and recompiling the same
        # program shape
        jax.clear_caches()
        proflog.reset()
        das2, _db2 = _tensor_das()
        ok2, _ = das2.query_answer(_inherit_query())
        assert ok2
        warm = proflog.snapshot()
        assert warm["compiles"] == 1
        assert warm["persistent_cache_hits"] == 1, warm
        # the cache-served compile's wall time stays OUT of cold_start_s
        assert warm["cold_start_s"] == 0.0
        assert warm["compile_s"] > 0
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_min_t
        )
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", prev_min_b
        )
        reset_cache()


# -- serving surfaces ------------------------------------------------------


def test_programs_in_service_stats_and_prometheus(ledger):
    from das_tpu.service.server import DasService

    svc = DasService(backend="tensor")
    stats = svc.coalescer_stats()
    progs = stats["programs"]
    for key in ("enabled", "compiles", "compile_s", "hit_rate",
                "cold_start_s", "persistent_cache_hits",
                "budget_vs_actual"):
        assert key in progs
    text = svc.metrics_text()
    assert "das_tpu_obs_programs_compiles" in text
    assert "das_tpu_obs_programs_compile_s" in text
    assert "das_tpu_obs_programs_cold_start_s" in text
    assert "das_tpu_obs_prof_compile_ms" in text


def test_compile_span_lands_in_trace_ring(ledger):
    obs.configure(enabled=True)
    obs.reset()
    try:
        das, _db = _tensor_das()
        ok, _ = das.query_answer(_inherit_query())
        assert ok
        comp = [e for e in obs.events() if e[0] == "prof.compile"]
        assert comp, "compile span must land when dastrace is on too"
        # the dedicated compile lane (scripts/dump_trace.py renders it
        # as its own Perfetto process row)
        assert comp[0][6] == "compile"
    finally:
        obs.reset()
        obs.configure(enabled=False)


# -- bench integration -----------------------------------------------------


def test_bench_section_delta_helper(ledger):
    sys.path.insert(0, str(REPO))
    import bench

    das, _db = _tensor_das()

    def section():
        das.query_answer(_inherit_query())
        return {"x": 1}

    out = bench._with_programs(section)
    assert out["x"] == 1
    assert out["programs_compiled"] >= 1
    assert out["compile_s"] > 0


# -- bench_diff: the regression gate ---------------------------------------


def test_bench_diff_committed_trajectory_passes():
    bd = _bench_diff()
    assert bd.main(["--self-check"]) == 0


def test_bench_diff_synthetic_regression_fails(tmp_path):
    bd = _bench_diff()
    rec = json.loads((REPO / "BENCH_SELF_r05.json").read_text())
    rec["value"] = rec["value"] * 10  # 10x the headline latency
    p = tmp_path / "regressed.json"
    p.write_text(json.dumps(rec))
    assert bd.main(["--candidate", str(p)]) == 1


def test_bench_diff_throughput_and_identity_gates(tmp_path):
    bd = _bench_diff()
    rec = json.loads((REPO / "BENCH_SELF_r05.json").read_text())
    rec["extra"]["pattern_matches_per_sec"] = 10  # collapse throughput
    rec["extra"]["matches"] = 9999                # changed answer count
    p = tmp_path / "regressed2.json"
    p.write_text(json.dumps(rec))
    assert bd.main(["--candidate", str(p)]) == 1


def test_bench_diff_honesty_interpret_never_gates_device(tmp_path):
    bd = _bench_diff()
    rec = json.loads((REPO / "BENCH_SELF_r05.json").read_text())
    rec["value"] = rec["value"] * 100
    rec["extra"]["platform"] = "cpu"  # interpret-class record
    p = tmp_path / "cpu.json"
    p.write_text(json.dumps(rec))
    assert bd.main(["--candidate", str(p)]) == 0


def test_bench_diff_parse_errors_exit_2(tmp_path):
    bd = _bench_diff()
    p = tmp_path / "garbage.json"
    p.write_text("{not json")
    assert bd.main(["--candidate", str(p)]) == 2
    q = tmp_path / "notarecord.json"
    q.write_text(json.dumps({"hello": 1}))
    assert bd.main(["--candidate", str(q)]) == 2


def test_bench_diff_cli_subprocess():
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "bench_diff.py"),
         "--self-check"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "pass" in proc.stdout


# -- DL016 -----------------------------------------------------------------


def test_dl016_clean_tree():
    from das_tpu.analysis import run_analysis

    findings = run_analysis([REPO / "das_tpu"], rules=["DL016"])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_dl016_fixture_corpus():
    from das_tpu.analysis import run_analysis

    bad = run_analysis([FIXTURES / "dl016_bad.py"], rules=["DL016"])
    msgs = "\n".join(f.message for f in bad)
    assert "build_uninstrumented" in msgs, msgs  # missing ledger hook
    assert "surprise_builder" in msgs, msgs      # undeclared scope
    assert "bare_name_builder" in msgs, msgs     # `from jax import jit`
    assert "typo_site" in msgs, msgs             # undeclared hook label
    assert "retired_builder" in msgs, msgs       # stale registry entry
    assert "outside any function" in msgs, msgs  # import-time compile
    assert len(bad) == 6, msgs
    good = run_analysis([FIXTURES / "dl016_good.py"], rules=["DL016"])
    assert good == [], "\n".join(f.render() for f in good)


def test_dl016_partial_suppresses_stale_only():
    from das_tpu.analysis import run_analysis

    partial = run_analysis(
        [FIXTURES / "dl016_bad.py"], rules=["DL016"], partial=True
    )
    msgs = "\n".join(f.message for f in partial)
    assert "surprise_builder" in msgs and "build_uninstrumented" in msgs
    assert "retired_builder" not in msgs, (
        "--changed-only runs must skip the stale-entry leg"
    )


def test_dl016_catches_deleted_hook_on_real_builder(tmp_path):
    """Mutated-copy regression: strip build_fused's instrument() call —
    re-introducing an unledgered program builder must fail lint."""
    from das_tpu.analysis import run_analysis

    src = (REPO / "das_tpu/query/fused.py").read_text()
    needle = (
        "    return obs.proflog.instrument(\n"
        '        "fused", obs.proflog.sig_digest(sig, count_only), '
        "jax.jit(fn),\n"
        "        model_bytes=partial(program_model_bytes, sig),\n"
        "    ), names"
    )
    assert src.count(needle) == 1, "fused.py build_fused layout changed"
    mutated = tmp_path / "fused.py"
    mutated.write_text(src.replace(needle, "    return jax.jit(fn), names"))
    findings = run_analysis(
        [mutated, REPO / "das_tpu/obs/proflog.py"],
        rules=["DL016"], partial=True,
    )
    assert any(
        "fused.build_fused" in f.message and "no" in f.message
        for f in findings
    ), "\n".join(f.render() for f in findings)
    # the committed module next to the registry stays clean
    clean = run_analysis(
        [REPO / "das_tpu/query/fused.py", REPO / "das_tpu/obs/proflog.py"],
        rules=["DL016"], partial=True,
    )
    assert clean == [], "\n".join(f.render() for f in clean)


def test_program_sites_registry_pinned():
    """The DL004-idiom test leg: instrumenting or exempting a program
    site is a reviewed change HERE, not silent drift."""
    instrumented = {
        scope: label
        for scope, label in proflog.PROGRAM_SITES.items()
        if label is not None
    }
    assert instrumented == {
        "fused.build_fused": "fused",
        "fused.build_fused_tree": "fused_tree",
        "fused.build_fused_exact": "fused_exact",
        "fused.FusedExecutor._run_batch_group": "count_batch",
        "fused.FusedExecutor.build_count_loop": "count_loop",
        "fused_sharded._ShardedExecJob.dispatch": "sharded",
        "fused_sharded._ShardedTreeExecJob._build": "sharded_tree",
        "common.run_kernel": "kernel",
        "common.run_grid_kernel": "kernel_grid",
    }
