#!/usr/bin/env python
"""dump_trace.py — render a das_tpu obs trace as Perfetto-loadable
Chrome trace-event JSON (ISSUE 12 exporter).

Two modes:

  * demo (default): build a small bio KB, enable tracing AND the
    program ledger, run a 3-var conjunctive workload (plus grounded
    repeats for cache-hit events and one incremental commit for the
    invalidation event) through the serving coalescer, and write the
    resulting trace — the acceptance artifact: submit → drain → plan →
    dispatch → settle → answer spans with route/est-vs-actual
    attributes, one lane per tenant/worker, plus a "compile" lane with
    one prof.compile span per XLA compile the workload paid (ISSUE 14 —
    the per-query spans show WHERE first-contact latency went).

        JAX_PLATFORMS=cpu python scripts/dump_trace.py -o /tmp/das_trace.json

  * `--self`: no workload — dump whatever the CURRENT process recorder
    holds (importable `dump_current(path)` for embedding in services).

Open the output at https://ui.perfetto.dev or chrome://tracing.  With
DAS_TPU_TRACE_JAX=1 / DAS_TPU_TRACE_DIR the same run also captures a
jax.profiler device trace to correlate against (obs/jaxprof.py).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def dump_current(path: str) -> str:
    """Write the current process recorder's ring to `path`."""
    from das_tpu import obs

    return obs.dump_chrome_trace(obs.events(), path)


def _demo_workload(n_clients: int, scale: float):
    from das_tpu import obs
    from das_tpu.api.atomspace import (
        DistributedAtomSpace,
        QueryOutputFormat,
    )
    from das_tpu.core.config import DasConfig
    from das_tpu.models.bio import build_bio_atomspace
    from das_tpu.query.ast import And, Link, Node, Variable
    from das_tpu.service.coalesce import QueryCoalescer
    from das_tpu.service.server import _Tenant
    from das_tpu.storage.tensor_db import TensorDB

    obs.configure(enabled=True)
    obs.reset()
    # program ledger on (ISSUE 14): every XLA compile the workload pays
    # lands as a prof.compile span in a dedicated "compile" Perfetto
    # lane, next to the serving lanes it stalls
    obs.proflog.configure(enabled=True)
    obs.proflog.reset()
    cfg = DasConfig.from_env()
    obs.maybe_start_trace(cfg)

    data, genes, _procs = build_bio_atomspace(
        n_genes=max(64, int(1000 * scale)),
        n_processes=max(16, int(200 * scale)),
        members_per_gene=5,
        n_interactions=max(128, int(2000 * scale)),
    )
    db = TensorDB(data, cfg)
    das = DistributedAtomSpace(database_name="trace-demo", db=db)
    tenant = _Tenant("trace-demo", das)
    coal = QueryCoalescer()

    three_var = And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Variable("V1"), Variable("V2")], True),
    ])

    def grounded(g):
        name = das.get_node_name(g)
        return And([
            Link("Member", [Node("Gene", name), Variable("V3")], True),
            Link("Member", [Variable("V2"), Variable("V3")], True),
            Link("Interacts", [Node("Gene", name), Variable("V2")], True),
        ])

    # the 3-var acceptance query plus grounded per-client queries
    # (repeats exercise the cache-hit lifecycle arm)
    workload = [three_var] + [
        grounded(genes[i % 8]) for i in range(n_clients - 1)
    ]
    futs = [
        coal.submit(tenant, q, QueryOutputFormat.HANDLE) for q in workload
    ]
    for f in futs:
        f.result(timeout=600)
    # the same workload again: delta-versioned cache hits (zero-dispatch
    # answers) land as cache.hit events on the trace
    futs = [
        coal.submit(tenant, q, QueryOutputFormat.HANDLE) for q in workload
    ]
    for f in futs:
        f.result(timeout=600)
    # one incremental commit -> commit.delta + cache.invalidate events
    das.load_metta_text(
        '(: "GENE:TRACE" Gene)\n(: "GO:TRACE" BiologicalProcess)\n'
        '(Member "GENE:TRACE" "GO:TRACE")'
    )
    futs = [
        coal.submit(tenant, workload[1], QueryOutputFormat.HANDLE)
        for _ in range(2)
    ]
    for f in futs:
        f.result(timeout=600)
    time.sleep(0.1)  # let the worker's settle span land in the ring
    obs.maybe_stop_trace()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-o", "--out", default="/tmp/das_trace.json")
    ap.add_argument(
        "--self", action="store_true", dest="self_only",
        help="dump the current recorder ring; run no demo workload",
    )
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--scale", type=float, default=0.1,
                    help="bio KB size factor (default 0.1)")
    args = ap.parse_args(argv)
    if not args.self_only:
        _demo_workload(args.clients, args.scale)
    path = dump_current(args.out)
    with open(path) as f:
        n = len(json.load(f)["traceEvents"])
    print(f"wrote {n} trace events to {path} — open in ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    sys.exit(main())
