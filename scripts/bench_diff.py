#!/usr/bin/env python
"""bench_diff.py — the bench-history regression gate (ISSUE 14).

The repo commits a perf trajectory (BENCH_r*.json driver records,
BENCH_SELF_r*.json full self-run records) that until now nothing
machine-compared: a regression in the headline device latency or the
serving throughput would ship silently as long as tests stayed green.
This script makes the trajectory load-bearing:

    python scripts/bench_diff.py                      # BENCH_FULL.json
    python scripts/bench_diff.py --candidate rec.json # explicit record
    python scripts/bench_diff.py --self-check         # newest committed
                                                      # vs its own prior
                                                      # trajectory (CI)

A candidate record is compared per-metric against the BEST comparable
committed value (not the newest: r01/r02 measured the headline
host-visible before the device-only methodology landed, so
nearest-neighbor deltas would gate on a methodology change, not a
regression).  Each gated metric declares its direction and an allowed
regression factor; crossing it exits 1 with one line per finding.

Honesty rules (the `interpret: true` contract the kernel A/Bs
established):

  * records gate only WITHIN a platform class — an `interpret`/CPU
    candidate is never measured against the committed device (TPU)
    trajectory and can never fail it (there is no wire to hide and no
    Mosaic compile; the numbers are structural, not perf claims), and
    a device record is never measured against a CPU baseline;
  * a candidate with no committed baseline in its class passes with a
    note — absence of history is not a regression;
  * `matches` is an identity gate, not a threshold: a changed answer
    count at the pinned workload scale means the WORKLOAD or the
    answers changed, which no perf threshold should paper over.

Exit codes: 0 = pass (or nothing comparable), 1 = regression(s),
2 = usage/parse error.  Thresholds are deliberately generous (they
bound catastrophe, not noise — run-to-run jitter on shared hardware is
real); tighten per metric as the trajectory stabilizes
(ARCHITECTURE §15).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass(frozen=True)
class Metric:
    """One gated headline metric.

    `paths`: alternative key paths into the record (full records nest
    serving figures under extra.serving; the compact headline flattens
    them) — first present wins.  `direction`: "lower" / "higher" /
    "equal".  `factor`: allowed regression multiple vs the best
    comparable committed value — e.g. lower/1.5 fails a candidate more
    than 1.5x the best committed latency; higher/0.5 fails a candidate
    under half the best committed throughput; ignored for "equal"."""

    name: str
    paths: Tuple[Tuple[str, ...], ...]
    direction: str
    factor: float


#: the gated metric table (per-metric thresholds, ISSUE 14) — the
#: compact-headline fields that constitute the perf contract.  compile_s
#: (also compact, this PR) is recorded but NOT gated yet: the ledger
#: needs a few committed records before a compile-time ceiling is
#: honest.
METRICS: Tuple[Metric, ...] = (
    Metric("value", (("value",),), "lower", 1.5),
    Metric("vs_baseline", (("vs_baseline",),), "higher", 0.5),
    Metric(
        "pattern_matches_per_sec",
        (("extra", "pattern_matches_per_sec"),), "higher", 0.5,
    ),
    Metric(
        "batched_ms_per_query",
        (("extra", "batched_ms_per_query"),), "lower", 1.5,
    ),
    Metric(
        "host_visible_p50_ms",
        (("extra", "host_visible_p50_ms"),), "lower", 1.5,
    ),
    Metric(
        "open_loop_ms_per_query",
        (("extra", "serving", "served_ms_per_query"),
         ("extra", "open_loop_ms_per_query")), "lower", 2.0,
    ),
    Metric(
        "open_loop_p99_ms",
        (("extra", "serving", "open_loop_p99_ms"),
         ("extra", "open_loop_p99_ms")), "lower", 2.0,
    ),
    Metric("matches", (("extra", "matches"),), "equal", 0.0),
)


def lookup(record: Dict, metric: Metric) -> Optional[float]:
    for path in metric.paths:
        node: Any = record
        for key in path:
            if not isinstance(node, dict) or key not in node:
                node = None
                break
            node = node[key]
        if isinstance(node, (int, float)) and not isinstance(node, bool):
            return float(node)
    return None


#: platforms that count as accelerator ("device") records — any
#: platform string NOT in either set is its own class, so an exotic
#: backend never cross-gates against cpu OR tpu history
_DEVICE_PLATFORMS = frozenset(("tpu", "gpu", "cuda", "rocm"))
_INTERPRET_PLATFORMS = frozenset(("cpu",))


def record_class(record: Dict, default: str = "interpret") -> str:
    """Platform class for the honesty rules: "device" for accelerator
    records, "interpret" for CPU, the platform string itself for
    anything else (an unknown backend gates only against its own
    kind), `default` when the record carries no platform at all.  Full
    records carry extra.platform; compact headlines don't — callers
    pass the class they KNOW (--self-check reads the full records)."""
    platform = (record.get("extra") or {}).get("platform")
    if platform is None:
        return default
    if platform in _DEVICE_PLATFORMS:
        return "device"
    if platform in _INTERPRET_PLATFORMS:
        return "interpret"
    return str(platform)


def _tail_record(driver: Dict) -> Optional[Dict]:
    """BENCH_r*.json are driver captures {n, cmd, rc, tail}; the tail
    holds the bench's final stdout — find the LAST parseable record
    with a `metric` key (the compact headline prints last)."""
    tail = driver.get("tail", "")
    best = None
    for m in re.finditer(r"\{", tail):
        try:
            obj = json.loads(tail[m.start():])
        except Exception:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            best = obj
    return best


def load_trajectory(repo: str = REPO) -> List[Tuple[str, Dict]]:
    """(name, record) for every parseable committed bench record,
    ordered by round number (BENCH_SELF_r04_run1 sorts after r04).
    Unparseable files are skipped: the gate compares history, it does
    not curate it."""
    out: List[Tuple[str, Dict]] = []
    for path in glob.glob(os.path.join(repo, "BENCH*_r*.json")) + glob.glob(
        os.path.join(repo, "BENCH_r*.json")
    ):
        name = os.path.basename(path)
        m = re.search(r"_r(\d+)(?:_run(\d+))?\.json$", name)
        if not m:
            continue
        try:
            with open(path) as fh:
                d = json.load(fh)
        except Exception:
            continue
        rec = d if "metric" in d else _tail_record(d)
        if rec is None:
            continue
        key = (int(m.group(1)), int(m.group(2) or 0), name)
        out.append((key, (name, rec)))
    out.sort(key=lambda kv: kv[0])
    seen = set()
    uniq = []
    for _key, (name, rec) in out:
        if name in seen:
            continue
        seen.add(name)
        uniq.append((name, rec))
    return uniq


@dataclass
class Delta:
    metric: str
    status: str            # "ok" | "regressed" | "skipped"
    candidate: Optional[float]
    best: Optional[float]
    best_from: Optional[str]
    note: str = ""


def compare(candidate: Dict, baselines: List[Tuple[str, Dict]],
            candidate_class: str) -> List[Delta]:
    """Per-metric verdicts for `candidate` against the best comparable
    committed value.  Baselines outside the candidate's platform class
    are excluded wholesale (the honesty rule)."""
    comparable = [
        (name, rec) for name, rec in baselines
        if record_class(rec) == candidate_class
    ]
    out: List[Delta] = []
    for metric in METRICS:
        cand = lookup(candidate, metric)
        if cand is None:
            out.append(Delta(metric.name, "skipped", None, None, None,
                             "candidate does not report it"))
            continue
        vals = [
            (lookup(rec, metric), name) for name, rec in comparable
        ]
        vals = [(v, n) for v, n in vals if v is not None]
        if not vals:
            out.append(Delta(metric.name, "skipped", cand, None, None,
                             "no comparable committed baseline"))
            continue
        if metric.direction == "lower":
            best, src = min(vals)
            bad = cand > best * metric.factor
        elif metric.direction == "higher":
            best, src = max(vals)
            bad = cand < best * metric.factor
        else:  # equal — identity gate against the NEWEST comparable
            best, src = vals[-1]
            bad = cand != best
        out.append(Delta(
            metric.name, "regressed" if bad else "ok", cand, best, src,
        ))
    return out


def render(deltas: List[Delta], candidate_name: str,
           candidate_class: str) -> int:
    regressions = [d for d in deltas if d.status == "regressed"]
    print(f"bench_diff: {candidate_name} [{candidate_class}] vs "
          f"committed trajectory")
    for d in deltas:
        if d.status == "skipped":
            print(f"  - {d.metric}: skipped ({d.note})")
        elif d.status == "ok":
            print(f"  - {d.metric}: ok ({d.candidate:g} vs best "
                  f"{d.best:g} from {d.best_from})")
        else:
            print(f"  - {d.metric}: REGRESSED ({d.candidate:g} vs best "
                  f"{d.best:g} from {d.best_from})")
    if regressions:
        print(f"bench_diff: {len(regressions)} regression(s) — "
              "the committed trajectory is load-bearing; either fix the "
              "regression or commit a new record with the change "
              "explained")
        return 1
    print("bench_diff: pass")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--candidate", default=None,
        help="candidate record JSON (full or compact headline); '-' = "
        "stdin; default BENCH_FULL.json in the repo root",
    )
    ap.add_argument("--repo", default=REPO)
    ap.add_argument(
        "--platform", choices=("auto", "device", "interpret"),
        default="auto",
        help="candidate platform class when the record does not carry "
        "extra.platform (auto = interpret — a classless record never "
        "gates the device trajectory)",
    )
    ap.add_argument(
        "--self-check", action="store_true",
        help="gate the NEWEST committed record against its own prior "
        "trajectory (the CI smoke: proves the committed history passes "
        "its own gate and the parser still reads every record)",
    )
    args = ap.parse_args(argv)

    trajectory = load_trajectory(args.repo)
    if args.self_check:
        if len(trajectory) < 2:
            print("bench_diff: fewer than 2 committed records — "
                  "nothing to self-check")
            return 0
        name, candidate = trajectory[-1]
        baselines = trajectory[:-1]
        cls = record_class(candidate)
        return render(compare(candidate, baselines, cls), name, cls)

    path = args.candidate or os.path.join(args.repo, "BENCH_FULL.json")
    try:
        if path == "-":
            candidate = json.load(sys.stdin)
            name = "<stdin>"
        else:
            with open(path) as fh:
                candidate = json.load(fh)
            name = os.path.basename(path)
    except Exception as e:
        print(f"bench_diff: cannot read candidate: {e!r}")
        return 2
    if "metric" not in candidate:
        print("bench_diff: candidate is not a bench record "
              "(no `metric` key)")
        return 2
    default_cls = (
        args.platform if args.platform != "auto" else "interpret"
    )
    cls = record_class(candidate, default=default_cls)
    return render(compare(candidate, trajectory, cls), name, cls)


if __name__ == "__main__":
    sys.exit(main())
