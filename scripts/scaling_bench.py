#!/usr/bin/env python
"""Multi-device scaling table for the sharded backend (VERDICT r04 item 4).

Builds a >=1M-link bio KB once, then for 1/2/4/8 shards on the virtual CPU
mesh measures:

  * partition_s   — ShardedTables build (round-robin partition + slab indexes)
  * probe_join_s  — the fused sharded conjunctive program (shard-local
                    probes + all_to_all/broadcast join), device time
  * materialize_s — result gather + host assignment decode
  * commit_s      — a 10-expression incremental commit (append_delta path)
  * per_shard_mb  — bytes of ONE shard's slab of the arity-2 bucket
  * result_cap    — the per-shard result-table capacity of the probe query

The load-bearing assertion (collective-shape regression guard): per-shard
slab bytes and the per-shard result capacity must SHRINK as shards double —
an accidental all-gather of a table that should stay partitioned, or a
globally-sized per-shard buffer, shows up here as a flat line.  Wall times
on a 1-core host are DIRECTIONAL ONLY (all virtual devices share the core;
real speedup needs real chips), but the buffer shapes are exact.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/scaling_bench.py [--scale 1.0]
Emits one JSON line per shard count and a final merged JSON line.
"""

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import das_tpu  # noqa: F401
import jax

jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

def median_time(fn, repeats=3):
    out = None
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn()
        times.append(time.perf_counter() - t0)
    return statistics.median(times), out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0,
                    help="KB size multiplier (1.0 => ~1.05M links)")
    ap.add_argument("--shards", default="1,2,4,8")
    args = ap.parse_args(argv)

    from das_tpu.models.bio import build_bio_atomspace
    from das_tpu.parallel.mesh import make_mesh
    from das_tpu.parallel.fused_sharded import get_sharded_executor
    from das_tpu.parallel.sharded_db import ShardedDB
    from das_tpu.query import compiler as qc
    from das_tpu.query.ast import And, Link, Node, PatternMatchingAnswer, Variable
    from das_tpu.storage.atom_table import load_metta_text

    s = args.scale
    t0 = time.perf_counter()
    data, genes, processes = build_bio_atomspace(
        n_genes=int(150_000 * s), n_processes=int(15_000 * s),
        members_per_gene=5, n_interactions=int(150_000 * s),
        n_evaluations=0,
    )
    nodes, links = data.count_atoms()
    build_s = time.perf_counter() - t0
    print(f"[scaling] KB: {nodes} nodes, {links} links in {build_s:.1f}s",
          file=sys.stderr)

    gene_name = "GENE:0000000"

    def grounded_query(g):
        return And([
            Link("Member", [Node("Gene", g), Variable("V3")], True),
            Link("Member", [Variable("V2"), Variable("V3")], True),
        ])

    def commit_text(S):
        # unique names per shard count: mutations accumulate (20 atoms per
        # S, negligible vs the base KB) instead of paying a full KB rebuild
        return (
            ''.join(f'(: "GENE:SC{S}_{i}" Gene)\n' for i in range(10))
            + ''.join(
                f'(Interacts "GENE:SC{S}_{i}" "GENE:SC{S}_{(i + 1) % 10}")\n'
                for i in range(10)
            )
        )

    rows = []
    expected = None
    for S in [int(x) for x in args.shards.split(",")]:
        t0 = time.perf_counter()
        db = ShardedDB(data, mesh=make_mesh(S))
        partition_s = time.perf_counter() - t0
        bucket = db.tables.buckets[2]
        # one shard's slab bytes across the arity-2 array family
        per_shard = sum(
            arr.nbytes // S
            for arr in (
                [bucket.type_id, bucket.ctype, bucket.targets,
                 bucket.targets_sorted, bucket.key_type,
                 bucket.order_by_type, bucket.key_ctype,
                 bucket.order_by_ctype]
                + bucket.key_type_pos + bucket.order_by_type_pos
                + bucket.key_pos + bucket.order_by_pos
            )
        )
        ex = get_sharded_executor(db)
        plans = qc.plan_query(db, grounded_query(gene_name))
        if plans is None:
            raise RuntimeError("grounded query must compile")

        def probe_join():
            res = ex.execute(plans)
            jax.block_until_ready(res.vals)
            return res

        probe_join()  # compile
        probe_join_s, res = median_time(probe_join)
        result_cap = int(res.vals.shape[-2] if res.vals.ndim == 3
                         else res.vals.shape[0])

        def materialize():
            answer = PatternMatchingAnswer()
            matched = db.query_sharded(grounded_query(gene_name), answer)
            if not matched:
                raise RuntimeError("sharded query returned no match")
            return answer

        mat_s, answer = median_time(materialize)
        materialize_s = max(mat_s - probe_join_s, 0.0)
        if expected is None:
            expected = len(answer.assignments)
        if len(answer.assignments) != expected:
            raise RuntimeError(f"answers diverge at S={S}")

        load_metta_text(commit_text(S), db.data)
        t0 = time.perf_counter()
        db.refresh()
        commit_s = time.perf_counter() - t0

        row = {
            "shards": S,
            "partition_s": round(partition_s, 3),
            "probe_join_s": round(probe_join_s, 4),
            "materialize_s": round(materialize_s, 4),
            "commit_s": round(commit_s, 3),
            "per_shard_mb": round(per_shard / 2**20, 1),
            "result_cap": result_cap,
            "answers": len(answer.assignments),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    # collective-shape guard: per-shard buffers must shrink as S doubles
    for a, b in zip(rows, rows[1:]):
        ratio = b["per_shard_mb"] / max(a["per_shard_mb"], 1e-9)
        if ratio >= 0.75:  # explicit: must survive python -O
            raise RuntimeError(
                f"per-shard slab did not shrink {a['shards']}->{b['shards']} "
                f"shards ({a['per_shard_mb']} -> {b['per_shard_mb']} MB): "
                "a buffer scales with the GLOBAL table"
            )
        if b["result_cap"] > a["result_cap"]:
            raise RuntimeError(
                f"per-shard result capacity grew {a['shards']}->{b['shards']}"
            )
    merged = {"kb_nodes": nodes, "kb_links": links, "scale": s,
              "table": rows, "buffers_partitioned": True}
    print(json.dumps(merged), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
