#!/usr/bin/env python
"""DAS benchmark harness: the reference's three query layouts on das_tpu.

Role of /root/reference/scripts/benchmark.py:193-335, with the
DB-architecture axis replaced by the das_tpu backend axis
(memory | tensor | sharded) and the private bio KB replaced by the
reproducible synthetic ontology atomspace (das_tpu/models/bio.py):

  QUERY_1  _same_biological_process — N-way And of Member links
           (benchmark.py:89-93)
  QUERY_2  _same_or_inherited_biological_process — nested And/Or with
           Inheritance LinkTemplates (benchmark.py:95-113)
  QUERY_3  multi-stage substring -> List -> Member pipeline
           (benchmark.py:254-289)

`BenchmarkResults` keeps the reference's reporting shape (runs, matched,
total, mean±stdev per query).
"""

import argparse
import random
import sys
import time

sys.path.insert(0, ".")

import numpy as np

import das_tpu  # noqa: F401

from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.models.bio import build_bio_ontology_atomspace
from das_tpu.query.ast import (
    And,
    Link,
    LinkTemplate,
    Node,
    Or,
    PatternMatchingAnswer,
    TypedVariable,
    Variable,
)


def same_biological_process(gene_names):
    v1 = Variable("V_BiologicalProcess")
    return And(
        [
            Link("Member", [Node("Gene", g), v1], True)
            for g in gene_names
        ]
    )


def same_or_inherited_biological_process(gene_names):
    v1 = Variable("V1_BiologicalProcess")
    v2 = Variable("V2_BiologicalProcess")
    tv1 = TypedVariable("V1_BiologicalProcess", "BiologicalProcess")
    tv2 = TypedVariable("V2_BiologicalProcess", "BiologicalProcess")
    tv3 = TypedVariable("V3_BiologicalProcess", "BiologicalProcess")
    g1, g2 = gene_names[0], gene_names[1]
    return And(
        [
            Link("Member", [Node("Gene", g1), v1], True),
            Or(
                [
                    And(
                        [
                            Link("Member", [Node("Gene", g2), v2], True),
                            LinkTemplate("Inheritance", [tv2, tv3], True),
                            LinkTemplate("Inheritance", [tv1, tv3], True),
                        ]
                    ),
                    Link("Member", [Node("Gene", g2), v1], True),
                ]
            ),
        ]
    )


class BenchmarkResults:
    def __init__(self, backend: str, layout: str):
        self.backend = backend
        self.layout = layout
        self.wall_time_per_run = []
        self.total_wall_time = None
        self.matched_queries = 0
        self.routes = {}
        self._t0 = None
        self._round_t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        self.total_wall_time = time.perf_counter() - self._t0

    def start_round(self):
        self._round_t0 = time.perf_counter()

    def stop_round(self):
        self.wall_time_per_run.append(time.perf_counter() - self._round_t0)

    def __repr__(self):
        wall = np.array(self.wall_time_per_run)
        routes = ", ".join(f"{k}={v}" for k, v in self.routes.items() if v)
        return "\n".join(
            [
                f"Backend: {self.backend}",
                f"Test layout: {self.layout}",
                f"{len(wall)} runs ({self.matched_queries} matched)",
                f"Total time: {self.total_wall_time:.3f} seconds",
                f"Average time per query: {np.mean(wall):.3f} seconds "
                f"(stdev: {np.std(wall):.3f}, p50: {np.median(wall):.3f})",
                f"Execution routes: {routes or 'none'}",
            ]
        )


class DasBenchmark:
    def __init__(self, das: DistributedAtomSpace, rounds: int, gene_count: int,
                 layout: str, seed: int = 7):
        self.das = das
        self.db = das.db
        self.rounds = rounds
        self.gene_count = gene_count
        self.layout = layout
        self.rng = random.Random(seed)
        self.all_genes = self.db.get_all_nodes("Gene", names=True)
        self.results = BenchmarkResults(das.config.backend, layout)

    def _genes(self):
        return self.rng.sample(self.all_genes, self.gene_count)

    def _timed_match(self, query):
        answer = PatternMatchingAnswer()
        self.results.start_round()
        matched = self.das._dispatch_query(query, answer)
        self.results.stop_round()
        if matched:
            self.results.matched_queries += 1

    def _query_1(self):
        self._timed_match(same_biological_process(self._genes()))

    def _query_2(self):
        self._timed_match(same_or_inherited_biological_process(self._genes()))

    def _query_3(self):
        v1 = Variable("v1")
        member_links = [
            Link("Member", [Node("Gene", g), v1], True) for g in self._genes()
        ]
        self.results.start_round()
        matched_any = False
        concept_handles = self.db.get_matched_node_name("Concept", "CoA")
        reactome_nodes = []
        for handle in concept_handles:
            pattern = Link(
                "List", [v1, Node("Concept", self.db.get_node_name(handle))], True
            )
            answer = PatternMatchingAnswer()
            if not self.das._dispatch_query(pattern, answer):
                continue
            for assignment in answer.assignments:
                reactome_nodes.append(assignment.mapping["v1"])
        uniprot_handles = []
        for r in reactome_nodes:
            pattern = Link("Member", [v1, Node("Reactome", self.db.get_node_name(r))], True)
            answer = PatternMatchingAnswer()
            if not self.das._dispatch_query(pattern, answer):
                continue
            for assignment in answer.assignments:
                uniprot_handles.append(assignment.mapping["v1"])
        for u in uniprot_handles:
            pattern = And(
                [
                    *member_links,
                    Link("Member", [Node("Uniprot", self.db.get_node_name(u)), v1], True),
                ]
            )
            answer = PatternMatchingAnswer()
            if self.das._dispatch_query(pattern, answer):
                matched_any = True
        self.results.stop_round()
        if matched_any:
            self.results.matched_queries += 1

    def run(self):
        from das_tpu.query import compiler as qc

        runner = {"1": self._query_1, "2": self._query_2, "3": self._query_3}[
            self.layout
        ]
        qc.reset_route_counts()
        self.results.start()
        for _ in range(self.rounds):
            runner()
        self.results.stop()
        self.results.routes = dict(qc.ROUTE_COUNTS)
        return self.results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="DAS TPU benchmark harness")
    ap.add_argument("--backend", default="tensor",
                    choices=("memory", "tensor", "sharded"))
    ap.add_argument("--layouts", default="1,2,3")
    ap.add_argument("--rounds", type=int, default=None,
                    help="override per-layout rounds (default 100/100/10)")
    ap.add_argument("--gene-count", type=int, default=2)
    ap.add_argument("--genes", type=int, default=1000)
    ap.add_argument("--processes", type=int, default=200)
    args = ap.parse_args(argv)

    data, _, _ = build_bio_ontology_atomspace(
        n_genes=args.genes, n_processes=args.processes
    )
    das = DistributedAtomSpace(backend=args.backend, data=data)
    das._refresh()
    default_rounds = {"1": 100, "2": 100, "3": 10}
    for layout in args.layouts.split(","):
        rounds = args.rounds or default_rounds[layout]
        bench = DasBenchmark(das, rounds, args.gene_count, layout)
        print("-" * 90)
        print(bench.run())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
