#!/usr/bin/env python
"""Store export/import in the reference's mongoexport text format.

    # export a checkpoint (or a .metta file) to <prefix>.{nodes,atom_types,links_*}
    python scripts/dump_das.py dump --checkpoint /path/ckpt  /tmp/out/animals
    python scripts/dump_das.py dump --metta data/samples/animals.metta /tmp/out/animals

    # import a dump (ours or a reference `mongodump` export) back into a checkpoint
    python scripts/dump_das.py load /tmp/out/animals --checkpoint-out /path/ckpt2

Counterpart of /root/reference/mongodump:1-8 (mongoexport | sort per
collection); file contents are byte-identical to a reference export of the
same store after `LC_ALL=C sort`.  See das_tpu/convert/dump.py.
"""

import argparse
import sys

sys.path.insert(0, ".")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="command", required=True)
    d = sub.add_parser("dump", help="export a store to <prefix>.<collection> files")
    src = d.add_mutually_exclusive_group(required=True)
    src.add_argument("--checkpoint", help="checkpoint directory to export")
    src.add_argument("--metta", help=".metta file/dir to load and export")
    d.add_argument("prefix", help="output file prefix")
    d.add_argument(
        "--include-empty", action="store_true",
        help="also write empty collection files",
    )
    ld = sub.add_parser("load", help="import a dump into a checkpoint")
    ld.add_argument("prefix", help="dump file prefix")
    ld.add_argument("--checkpoint-out", required=True)
    args = ap.parse_args()

    from das_tpu.convert import dump as dump_mod
    from das_tpu.storage import checkpoint
    from das_tpu.storage.atom_table import AtomSpaceData

    if args.command == "dump":
        if args.checkpoint:
            data = checkpoint.load(args.checkpoint)
        else:
            from das_tpu.ingest.pipeline import load_knowledge_base

            data = load_knowledge_base(AtomSpaceData(), args.metta)
        written = dump_mod.dump_store(
            data, args.prefix, include_empty=args.include_empty
        )
        nodes, links = data.count_atoms()
        print(f"dumped {nodes} nodes, {links} links -> {', '.join(written)}")
    else:
        data = dump_mod.load_dump(args.prefix)
        checkpoint.save(data, args.checkpoint_out, with_indexes=True)
        nodes, links = data.count_atoms()
        print(
            f"loaded {nodes} nodes, {links} links -> {args.checkpoint_out}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
