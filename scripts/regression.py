#!/usr/bin/env python
"""End-to-end query regression battery over the animals KB.

Role of /root/reference/scripts/regression.py:11-312 — load animals.metta,
run every operator/assignment combination, print the answers for manual
diffing.  Machine-checked equivalents live in tests/test_differential.py
(same battery diffed against the reference implementation's own engine);
this script is the human-inspectable runner, with a --backend axis.
"""

import argparse
import sys

sys.path.insert(0, ".")

import das_tpu  # noqa: F401

from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.models.animals import animals_metta
from das_tpu.query.ast import (
    And,
    Link,
    LinkTemplate,
    Node,
    Not,
    Or,
    PatternMatchingAnswer,
    TypedVariable,
    Variable,
)


def N(name):
    return Node("Concept", name)


def V(name):
    return Variable(name)


def queries():
    yield Link("Inheritance", [N("human"), N("mammal")], True)
    yield Link("Similarity", [N("human"), N("mammal")], False)
    yield Link("Similarity", [N("snake"), N("earthworm")], False)
    yield Link("Similarity", [N("earthworm"), N("snake")], False)
    yield Link("Inheritance", [V("V1"), N("mammal")], True)
    yield Link("Inheritance", [V("V1"), V("V2")], True)
    yield Link("Inheritance", [V("V1"), V("V1")], True)
    yield Link("Inheritance", [N("mammal"), V("V1")], True)
    yield Link("Similarity", [V("V1"), V("V2")], False)
    yield Link("Similarity", [N("human"), V("V1")], False)
    yield Link("Similarity", [V("V1"), N("human")], False)
    yield Not(Link("Inheritance", [N("human"), N("mammal")], True))
    yield Not(Link("Inheritance", [V("V1"), N("mammal")], True))
    yield And([
        Link("Inheritance", [V("V1"), V("V2")], True),
        Link("Inheritance", [V("V2"), V("V3")], True),
    ])
    yield And([
        Link("Inheritance", [V("V1"), V("V3")], True),
        Link("Inheritance", [V("V2"), V("V3")], True),
        Link("Similarity", [V("V1"), V("V2")], False),
    ])
    yield And([
        Link("Inheritance", [V("V1"), V("V3")], True),
        Link("Inheritance", [V("V2"), V("V3")], True),
        Not(Link("Similarity", [V("V1"), V("V2")], False)),
    ])
    yield Or([
        Link("Inheritance", [V("V1"), N("plant")], True),
        Link("Similarity", [V("V1"), N("snake")], False),
    ])
    yield LinkTemplate(
        "Inheritance",
        [TypedVariable("V1", "Concept"), TypedVariable("V2", "Concept")],
        True,
    )
    yield LinkTemplate(
        "Similarity",
        [TypedVariable("V1", "Concept"), TypedVariable("V2", "Concept")],
        False,
    )
    yield And([
        LinkTemplate(
            "Inheritance",
            [TypedVariable("V1", "Concept"), TypedVariable("V2", "Concept")],
            True,
        ),
        Link("Similarity", [V("V1"), V("V2")], False),
    ])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="animals KB query regression")
    ap.add_argument("--backend", default="memory",
                    choices=("memory", "tensor", "sharded"))
    args = ap.parse_args(argv)
    das = DistributedAtomSpace(backend=args.backend)
    das.load_metta_text(animals_metta())
    nodes, links = das.count_atoms()
    print(f"count_atoms: ({nodes}, {links})")
    for i, query in enumerate(queries()):
        answer = PatternMatchingAnswer()
        matched = das._dispatch_query(query, answer)
        print("=" * 80)
        print(f"[{i}] {query}")
        print(f"matched: {bool(matched)}  assignments: {len(answer.assignments)}")
        for assignment in sorted(str(a) for a in answer.assignments):
            print(f"  {assignment}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
