#!/usr/bin/env python
"""End-to-end query regression battery over the animals KB.

Native counterpart of /root/reference/scripts/regression.py:20-312,
enumerating the SAME ~55 match() calls in the SAME order with the SAME
output format, plus a --backend axis (memory | tensor | sharded).
tests/test_regression_battery.py diffs this script's normalized output
against the reference script itself running through the compat shim on
every backend; tests/test_differential.py separately diffs the engine
against the reference implementation's own algebra.
"""

import argparse
import sys

sys.path.insert(0, ".")

import das_tpu  # noqa: F401

from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.models.animals import animals_metta
from das_tpu.query.ast import (
    And,
    Link,
    LinkTemplate,
    Node,
    Not,
    Or,
    PatternMatchingAnswer,
    TypedVariable,
    Variable,
)


def N(name):
    return Node("Concept", name)


def V(name):
    return Variable(name)


def TV(name):
    return TypedVariable(name, "Concept")


def SET4():
    return Link("Set", [V("V1"), V("V2"), V("V3"), V("V4")], False)


def LIST4():
    return Link("List", [V("V1"), V("V2"), V("V3"), V("V4")], True)


def INH_V1V2():
    return Link("Inheritance", [V("V1"), V("V2")], True)


def SIM_V1V2():
    return Link("Similarity", [V("V1"), V("V2")], False)


def first_section():
    """The 48 pre-separator queries (regression.py:29-290, in order)."""
    yield N("human")
    yield Link("Inheritance", [N("human"), N("mammal")], True)
    yield Link("Similarity", [N("human"), N("mammal")], False)
    yield Link("Similarity", [N("snake"), N("earthworm")], False)
    yield Link("Similarity", [N("earthworm"), N("snake")], False)
    # nested links over grounded sub-expressions (regression.py:44-56)
    l1 = Link("Inheritance", [N("dinosaur"), N("reptile")], True)
    l2 = Link("Inheritance", [N("triceratops"), N("dinosaur")], True)
    yield Link("List", [l1, l2], True)
    yield Link("List", [l2, l1], True)
    yield Link("Set", [l1, l2], False)
    yield Link("Set", [l2, l1], False)
    yield Link("Inheritance", [N("human"), N("mammal")], True)
    yield Link("Inheritance", [N("monkey"), N("mammal")], True)
    yield Link("Inheritance", [N("chimp"), N("mammal")], True)
    yield Link("Similarity", [N("human"), N("monkey")], False)
    yield Link("Similarity", [N("chimp"), N("monkey")], False)
    yield Link("Inheritance", [V("V1"), N("mammal")], True)
    yield Link("Inheritance", [V("V1"), V("V2")], True)
    yield Link("Inheritance", [V("V1"), V("V1")], True)
    yield Link("Inheritance", [V("V2"), V("V1")], True)
    yield Link("Inheritance", [N("mammal"), V("V1")], True)
    yield Link("Inheritance", [N("animal"), V("V1")], True)
    yield Link("Similarity", [V("V1"), V("V2")], False)
    yield Link("Similarity", [N("human"), V("V1")], False)
    yield Link("Similarity", [V("V1"), N("human")], False)
    yield Link("List", [N("human"), N("ent"), V("V1"), V("V2")], True)
    yield Link("List", [N("human"), V("V1"), V("V2"), N("ent")], True)
    yield Link("List", [N("ent"), V("V1"), V("V2"), N("human")], True)
    yield Link("Set", [N("human"), N("ent"), V("V1"), V("V2")], False)
    yield Link("Set", [N("human"), V("V1"), V("V2"), N("ent")], False)
    yield Link("Set", [N("ent"), V("V1"), V("V2"), N("human")], False)
    yield Link("Set", [N("monkey"), V("V1"), V("V2"), N("chimp")], False)
    yield INH_V1V2()
    yield Link("Inheritance", [V("V2"), V("V3")], True)
    yield Not(Link("Inheritance", [N("human"), N("mammal")], True))
    yield Not(Link("Inheritance", [V("V1"), N("mammal")], True))
    yield Not(Link("Inheritance", [V("V1"), N("human")], True))
    yield And([INH_V1V2(), Link("Inheritance", [V("V2"), V("V3")], True)])
    yield And([INH_V1V2(), SIM_V1V2()])
    yield And([
        Link("Inheritance", [V("V1"), V("V3")], True),
        Link("Inheritance", [V("V2"), V("V3")], True),
        SIM_V1V2(),
    ])
    yield And([
        Link("Inheritance", [V("V1"), V("V3")], True),
        Link("Inheritance", [V("V2"), V("V3")], True),
        Not(SIM_V1V2()),
    ])
    yield And([SET4(), SIM_V1V2()])
    yield And([SET4(), Not(SIM_V1V2())])
    yield And([SET4(), INH_V1V2()])
    yield And([SET4(), Not(INH_V1V2())])
    yield And([SET4(), Not(INH_V1V2()), SIM_V1V2()])
    yield Or([SET4(), SIM_V1V2()])
    yield Or([Not(INH_V1V2()), SET4()])
    yield And([SET4(), Not(Or([INH_V1V2(), SIM_V1V2()]))])
    yield And([
        Or([SET4(), LIST4()]),
        Not(Or([INH_V1V2(), SIM_V1V2()])),
    ])


def second_section():
    """The 7 post-separator queries (regression.py:297-310)."""
    yield LinkTemplate("Inheritance", [TV("V1"), TV("V2")], True)
    yield LinkTemplate("Similarity", [TV("V1"), TV("V2")], False)
    yield Link("Inheritance", [V("V1"), V("V2")], True)
    yield Link("List", [V("V1"), V("V2")], True)
    yield LinkTemplate("List", [TV("V1"), TV("V2")], True)
    yield Link("Similarity", [N("human"), V("V1")], False)
    yield Link("Similarity", [V("V1"), N("human")], False)


def match(das, expression):
    """Reference match() (regression.py:10-17): same three prints."""
    print(f"Matching {expression}")
    answer = PatternMatchingAnswer()
    print(das._dispatch_query(expression, answer))
    print(answer)
    print(
        "--------------------------------------------------------------------------------"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="animals KB query regression")
    ap.add_argument("--backend", default="memory",
                    choices=("memory", "tensor", "sharded"))
    args = ap.parse_args(argv)
    print(
        "---------------------------- Integration tests ---------------------------------"
    )
    das = DistributedAtomSpace(backend=args.backend)
    das.load_metta_text(animals_metta())
    for query in first_section():
        match(das, query)
    print(
        "\n\n\n\n================================================================================\n"
    )
    print(das.db.get_all_nodes("Concept"))
    print(das.db.get_all_nodes("blah"))
    for query in second_section():
        match(das, query)
    das.clear_database()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
