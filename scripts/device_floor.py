#!/usr/bin/env python
"""Attribute the ~0.3 ms single-query device floor (VERDICT r04 item 8).

The headline `value` is the WIDTH SLOPE of the sequential fori_loop count
program — per-iteration device cost with dispatch/transport cancelled.
This experiment separates the two candidate attributions:

  * capacity-proportional work — the loop body probes/joins over
    capacity-PADDED buffers, so per-query cost should track KB size
    (probe capacity classes), shrinking on smaller stores;
  * fixed per-iteration floor — while-loop iteration overhead + fixed
    kernel shapes, flat across KB sizes.

Method: the same grounded 3-clause query family on bio KBs of increasing
size; per-query loop slope + the dispatch intercept (t1 - w1*slope: the
fixed cost of ONE dispatch+fetch, dominated by the tunnel RTT when
remote) at each size, plus the learned probe capacities for context.

Run on the TPU host:  python scripts/device_floor.py
Emits one JSON line per KB size and a merged final line.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import das_tpu  # noqa: F401
import jax


def main() -> int:
    import bench
    from das_tpu.core.config import DasConfig
    from das_tpu.models.bio import build_bio_atomspace
    from das_tpu.query import compiler
    from das_tpu.query.fused import get_executor
    from das_tpu.storage.tensor_db import TensorDB

    sizes = [
        ("14k", dict(n_genes=2_000, n_processes=200, members_per_gene=5,
                     n_interactions=1_500, n_evaluations=500)),
        ("140k", dict(n_genes=20_000, n_processes=2_000, members_per_gene=5,
                      n_interactions=15_000, n_evaluations=5_000)),
        ("1.4M", dict(n_genes=200_000, n_processes=20_000,
                      members_per_gene=5, n_interactions=150_000,
                      n_evaluations=50_000)),
    ]
    rows = []
    for label, cfg in sizes:
        data, _, _ = build_bio_atomspace(**cfg)
        nodes, links = data.count_atoms()
        db = TensorDB(data, DasConfig(initial_result_capacity=1 << 16))
        genes = db.get_all_nodes("Gene", names=True)
        plan_cache = {}

        def plans_for(w):
            if w not in plan_cache:
                plan_cache[w] = [
                    compiler.plan_query(db, bench.grounded_query(g))
                    for g in genes[:w]
                ]
            return plan_cache[w]

        ex = get_executor(db)
        w1, w2 = 16, 128
        run1, _ = ex.build_count_loop(plans_for(w1))
        run2, _ = ex.build_count_loop(plans_for(w2))
        t1 = bench._best_of(run1, 5)
        t2 = bench._best_of(run2, 5)
        slope = (t2 - t1) / (w2 - w1)
        if slope <= 0:  # clock noise swamped the width delta (bench.py:173)
            slope = t2 / w2
        slope_ms = slope * 1e3
        intercept_ms = max(t1 * 1e3 - w1 * slope_ms, 0.0)
        row = {
            "kb": label,
            "kb_links": links,
            "per_query_ms": round(slope_ms, 4),
            "dispatch_intercept_ms": round(intercept_ms, 2),
            "w1_s": round(t1, 4),
            "w2_s": round(t2, 4),
            "platform": jax.devices()[0].platform,
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
        # drop EVERYTHING holding the old KB (the closure cells and the
        # compiled loop executables pin db/genes) before the next build
        del db, data, ex, plan_cache, plans_for, run1, run2, genes
        import gc

        gc.collect()

    flat = rows[-1]["per_query_ms"] / max(rows[0]["per_query_ms"], 1e-9)
    merged = {
        "table": rows,
        # >3x growth across 100x KB size = capacity-proportional work;
        # <1.5x = fixed per-iteration floor
        "per_query_growth_14k_to_1p4M": round(flat, 2),
    }
    print(json.dumps(merged), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
