#!/usr/bin/env python
"""KB loader CLI (role of /root/reference/scripts/load_das.py:4-23).

Loads a MeTTa/Atomese knowledge base (file or directory) and optionally
writes a das_tpu checkpoint directory for fast resume.
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

import das_tpu  # noqa: F401

from das_tpu.api.atomspace import DistributedAtomSpace


def run(argv=None) -> int:
    ap = argparse.ArgumentParser(description="Load a knowledge base")
    ap.add_argument("--knowledge-base", required=True,
                    help="path to a .metta/.scm file or directory of them")
    ap.add_argument("--canonical", action="store_true",
                    help="use the fast canonical loader (normalized files)")
    ap.add_argument("--backend", default="tensor",
                    choices=("memory", "tensor", "sharded"))
    ap.add_argument("--checkpoint", default=None,
                    help="write a checkpoint directory after loading")
    args = ap.parse_args(argv)

    das = DistributedAtomSpace(backend=args.backend)
    t0 = time.perf_counter()
    if args.canonical:
        das.load_canonical_knowledge_base(args.knowledge_base)
    else:
        das.load_knowledge_base(args.knowledge_base)
    nodes, links = das.count_atoms()
    print(f"Loaded {nodes} nodes, {links} links in {time.perf_counter()-t0:.2f}s")
    if args.checkpoint:
        t0 = time.perf_counter()
        das.save_checkpoint(args.checkpoint)
        print(f"Checkpoint written to {args.checkpoint} in {time.perf_counter()-t0:.2f}s")
    return 0


if __name__ == "__main__":
    raise SystemExit(run())
