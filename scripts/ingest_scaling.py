#!/usr/bin/env python
"""Ingest thread-scaling measurement (VERDICT r04 item 7).

Generates a canonical .metta file once, then runs the native columnar
scanner (native/src/das_columnar.cc work-stealing pool) at 1/2/4/8 worker
threads, reporting expressions/s per setting and expressions/s/core.

On a 1-core host the pool CANNOT show wall-clock scaling (all threads
share the core; the honest figure is expr/s at workers=1) — the script
reports os.cpu_count() alongside so the numbers read correctly.

Run:  python scripts/ingest_scaling.py [--scale 0.1] [--workers 1,2,4,8]
Emits one JSON line per setting and a final merged line.
"""

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.1)
    ap.add_argument("--workers", default="1,2,4,8")
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args(argv)

    from das_tpu.ingest import native as native_mod
    from das_tpu.models.bio import write_bio_canonical

    if not native_mod.native_available():
        print(json.dumps({"error": "native scanner unavailable"}))
        return 1

    s = args.scale
    cfg = dict(
        n_genes=int(600_000 * s), n_processes=int(60_000 * s),
        members_per_gene=5, n_interactions=int(500_000 * s),
        n_evaluations=int(2_000_000 * s),
    )
    tmp = tempfile.mkdtemp(prefix="das_ingest_scaling_")
    path = os.path.join(tmp, "bio.metta")
    try:
        t0 = time.perf_counter()
        write_bio_canonical(path, **cfg)
        gen_s = time.perf_counter() - t0
        size_mb = os.path.getsize(path) / 1e6
        print(f"[ingest] {size_mb:.0f} MB generated in {gen_s:.0f}s",
              file=sys.stderr)

        rows = []
        links = None
        for w in [int(x) for x in args.workers.split(",")]:
            times = []
            for _ in range(args.repeats):
                t0 = time.perf_counter()
                data = native_mod.load_canonical_files_columnar(
                    [path], n_threads=w
                )
                times.append(time.perf_counter() - t0)
                if links is None:
                    _, links = data.count_atoms()
                del data
            t = statistics.median(times)
            row = {
                "workers": w,
                "parse_s": round(t, 2),
                "mb_per_s": round(size_mb / t, 1),
                "expr_per_s": round(links / t),
            }
            rows.append(row)
            print(json.dumps(row), flush=True)
        cores = os.cpu_count() or 1
        merged = {
            "file_mb": round(size_mb, 1),
            "links": links,
            "host_cores": cores,
            # best per-core figure over the rows: each row's cores-used is
            # min(workers, cores) — a plateaued multi-core scan must not
            # divide its best throughput by idle workers
            "expr_per_s_per_core": round(max(
                r["expr_per_s"] / min(r["workers"], cores) for r in rows
            )),
            "table": rows,
        }
        print(json.dumps(merged), flush=True)
        return 0
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
