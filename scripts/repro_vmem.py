"""Reproduce the v5e scoped-vmem OOM in the fori_loop count program
(VERDICT r03 weak #2).  Builds the bench's LARGE KB, then the same
build_count_loop programs bench.py's device_only_ms uses."""

import sys
import time

sys.path.insert(0, ".")

import das_tpu  # noqa: F401
from das_tpu.core.config import DasConfig
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.query import compiler
from das_tpu.query.ast import And, Link, Node, Variable
from das_tpu.query.fused import get_executor
from das_tpu.storage.tensor_db import TensorDB

LARGE = dict(n_genes=20000, n_processes=2000, members_per_gene=5,
             n_interactions=15000, n_evaluations=5000)


def grounded_query(gene_name):
    return And([
        Link("Member", [Node("Gene", gene_name), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Node("Gene", gene_name), Variable("V2")], True),
    ])


def main():
    t0 = time.time()
    data, _, _ = build_bio_atomspace(**LARGE)
    db = TensorDB(data, DasConfig(initial_result_capacity=1 << 16))
    print(f"build {time.time()-t0:.1f}s", flush=True)
    genes = db.get_all_nodes("Gene", names=True)
    ex = get_executor(db)
    for w in (16, 128):
        plans = [compiler.plan_query(db, grounded_query(g)) for g in genes[:w]]
        t0 = time.time()
        try:
            run, W = ex.build_count_loop(plans)
            counts, mx = run()
            print(f"W={w} OK build+run {time.time()-t0:.1f}s "
                  f"counts[:4]={counts[:4]}", flush=True)
        except Exception as e:
            print(f"W={w} FAIL after {time.time()-t0:.1f}s: {e!r}"[:2000],
                  flush=True)


if __name__ == "__main__":
    main()
