"""Shim for /root/reference/das/transaction.py (:1-10)."""

from das_tpu.api.atomspace import Transaction  # noqa: F401
