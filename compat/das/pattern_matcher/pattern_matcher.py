"""Shim for /root/reference/das/pattern_matcher/pattern_matcher.py (:21-748).

The assignment algebra and the logical-expression language re-export from
das_tpu (semantics proven identical to the reference engine by
tests/test_differential.py and tests/test_fuzz.py).  The composable
expression classes (`Link`, `LinkTemplate`, `Not`, `Or`, `And`) are thin
subclasses whose `matched(db, answer)` routes through the device compiler
first: reference call sites (scripts/regression.py:14,
scripts/benchmark.py:234) call `matched` directly on the expression, never
through `DistributedAtomSpace.query`, so without this hook the verbatim
reference scripts would silently stay on the host algebra.  On non-device
backends (MemoryDB) dispatch degrades to exactly the host evaluator.

`host_matched` exposes the undecorated host evaluator; compiler.dispatch
uses it as the fallback so a declined/overflowed device attempt never
re-enters `matched` and runs the device path twice.
"""

from das_tpu.query import ast as _ast
from das_tpu.query import compiler as _compiler
from das_tpu.query.assignment import (  # noqa: F401
    CONFIG,
    Assignment,
    Compatibility as CompatibilityStatus,
    CompositeAssignment,
    OrderedAssignment,
    UnorderedAssignment,
)
from das_tpu.query.ast import (  # noqa: F401
    Atom,
    LogicalExpression,
    Node,
    PatternMatchingAnswer,
    TypedVariable,
    Variable,
)


def _routed(cls):
    """Build a subclass whose matched() tries the device compiler first and
    whose host_matched() is the plain host evaluator."""

    def matched(self, db, answer):
        return _compiler.dispatch(db, self, answer, host=self.host_matched)

    def host_matched(self, db, answer):
        return cls.matched(self, db, answer)

    return type(
        cls.__name__, (cls,), {"matched": matched, "host_matched": host_matched}
    )


Link = _routed(_ast.Link)
LinkTemplate = _routed(_ast.LinkTemplate)
Not = _routed(_ast.Not)
Or = _routed(_ast.Or)
And = _routed(_ast.And)
