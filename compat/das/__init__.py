"""Reference-compatibility shim: the `das` package surface of the upstream
Distributed Atom Space, re-exported from das_tpu.

Purpose (BASELINE.json north star): unmodified reference artifacts —
/root/reference/scripts/regression.py, scripts/benchmark.py,
notebooks/QueryDAS.ipynb — run verbatim against the TPU-native backends with

    PYTHONPATH=/root/repo/compat:/root/repo

Module map (reference file → shim source):
  das/distributed_atom_space.py  → das_tpu.api.atomspace
  das/database/db_interface.py   → das_tpu.storage.interface + core.schema
  das/pattern_matcher/pattern_matcher.py
                                 → das_tpu.query.ast + query.assignment,
                                   with `matched()` additionally routed
                                   through the device compiler (the
                                   reference calls `expr.matched(db, ans)`
                                   directly, bypassing the API facade's
                                   dispatch — the shim restores the TPU
                                   execution path at that call site)
  das/expression_hasher.py       → das_tpu.core.hashing
  das/expression.py              → das_tpu.core.expression
  das/transaction.py             → das_tpu.api.atomspace.Transaction
  das/exceptions.py              → das_tpu.core.exceptions
  das/logger.py                  → das_tpu.utils.logger

Backend selection replaces the reference's Mongo/Redis env vars with
DAS_TPU_BACKEND (memory|tensor|sharded) and DAS_TPU_CHECKPOINT (persisted
store auto-attached at construction, standing in for the database servers).
"""
