"""Shim for /root/reference/das/expression_hasher.py (:4-60)."""

from das_tpu.core.hashing import (  # noqa: F401
    ExpressionHasher,
    StringExpressionHasher,
)
