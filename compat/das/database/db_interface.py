"""Shim for /root/reference/das/database/db_interface.py (:4-71)."""

from das_tpu.core.schema import UNORDERED_LINK_TYPES, WILDCARD  # noqa: F401
from das_tpu.storage.interface import DBInterface  # noqa: F401
