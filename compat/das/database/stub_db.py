"""Shim for /root/reference/das/database/stub_db.py (:20-188).

The reference StubDB is a hand-rolled dict fake over a readable-handle
animals fixture (handles like ``<Concept: human>``), used by its
pattern-matcher unit tests.  Here it is a TRANSLATION LAYER over the real
MemoryDB: the same fixture loads through the MeTTa parser into an
AtomSpaceData, every DBInterface method delegates to MemoryDB, and md5
handles are mapped to/from the reference's readable handle format at the
boundary — so the reference's own pattern_matcher_test.py exercises this
framework's storage + engine stack verbatim
(tests/test_reference_shim.py::test_reference_pattern_matcher_unit_tests_pass
runs a copy of that file with the shim on sys.path).

Readable handle formats (reference stub_db.py:8-18):
  node  ``<Type: name>``
  link  ``<Type: [target_handles...]>`` with targets sorted for the
        unordered types.
"""

from typing import Any, List, Tuple

from das_tpu.core.schema import UNORDERED_LINK_TYPES, WILDCARD
from das_tpu.storage.atom_table import load_metta_text
from das_tpu.storage.interface import DBInterface
from das_tpu.storage.memory_db import MemoryDB

#: the reference stub's fixture, 1:1 (stub_db.py:24-72) — note it is NOT
#: animals.metta: Similarity links appear in ONE orientation only (the
#: sample file stores the symmetric closure), and the List/Set families
#: its unit tests query are extra
_STUB_FIXTURE = """
(: Similarity Type)
(: Concept Type)
(: Inheritance Type)
(: List Type)
(: Set Type)
(: "human" Concept)
(: "monkey" Concept)
(: "chimp" Concept)
(: "snake" Concept)
(: "earthworm" Concept)
(: "rhino" Concept)
(: "triceratops" Concept)
(: "vine" Concept)
(: "ent" Concept)
(: "mammal" Concept)
(: "animal" Concept)
(: "reptile" Concept)
(: "dinosaur" Concept)
(: "plant" Concept)
(Similarity "human" "monkey")
(Similarity "human" "chimp")
(Similarity "chimp" "monkey")
(Similarity "snake" "earthworm")
(Similarity "rhino" "triceratops")
(Similarity "snake" "vine")
(Similarity "human" "ent")
(Inheritance "human" "mammal")
(Inheritance "monkey" "mammal")
(Inheritance "chimp" "mammal")
(Inheritance "mammal" "animal")
(Inheritance "reptile" "animal")
(Inheritance "snake" "reptile")
(Inheritance "dinosaur" "reptile")
(Inheritance "triceratops" "dinosaur")
(Inheritance "earthworm" "animal")
(Inheritance "rhino" "mammal")
(Inheritance "vine" "plant")
(Inheritance "ent" "plant")
(List (Inheritance "dinosaur" "reptile") (Inheritance "triceratops" "dinosaur"))
(Set (Inheritance "dinosaur" "reptile") (Inheritance "triceratops" "dinosaur"))
(List "human" "ent" "monkey" "chimp")
(List "human" "mammal" "triceratops" "vine")
(List "human" "monkey" "chimp")
(List "triceratops" "ent" "monkey" "snake")
(Set "triceratops" "vine" "monkey" "snake")
(Set "triceratops" "ent" "monkey" "snake")
(Set "human" "ent" "monkey" "chimp")
(Set "mammal" "monkey" "human" "chimp")
(Set "human" "monkey" "chimp")
"""


def _build_node_handle(node_type: str, node_name: str) -> str:
    return f"<{node_type}: {node_name}>"


class StubDB(DBInterface):
    def __init__(self):
        data = load_metta_text(_STUB_FIXTURE)
        self._db = MemoryDB(data)
        self._readable = {}
        self._md5 = {}
        for h, node in data.nodes.items():
            r = _build_node_handle(node.named_type, node.name)
            self._readable[h] = r
            self._md5[r] = h

        def readable(h: str) -> str:
            cached = self._readable.get(h)
            if cached is not None:
                return cached
            link = data.links[h]
            targets = [readable(t) for t in link.elements]
            if link.named_type in UNORDERED_LINK_TYPES:
                targets = sorted(targets)
            r = f"<{link.named_type}: {targets}>"
            self._readable[h] = r
            self._md5[r] = h
            return r

        for h in list(data.links):
            readable(h)

    # -- handle translation ------------------------------------------------

    def _to_md5(self, handle: str) -> str:
        if handle == WILDCARD:
            return WILDCARD
        return self._md5.get(handle, handle)

    def _to_readable(self, handle: str) -> str:
        return self._readable.get(handle, handle)

    # -- DBInterface -------------------------------------------------------

    def node_exists(self, node_type: str, node_name: str) -> bool:
        return self._db.node_exists(node_type, node_name)

    def link_exists(self, link_type: str, target_handles: List[str]) -> bool:
        # unordered existence is multiset existence: build the readable
        # handle (sorted for unordered types) and look it up — translating
        # targets in caller order would make Set/Similarity probes
        # order-sensitive, which the reference stub is not
        return self.get_link_handle(link_type, target_handles) in self._md5

    def get_node_handle(self, node_type: str, node_name: str) -> str:
        return _build_node_handle(node_type, node_name)

    def get_link_handle(self, link_type: str, target_handles: List[str]) -> str:
        targets = list(target_handles)
        if link_type in UNORDERED_LINK_TYPES:
            targets = sorted(targets)
        return f"<{link_type}: {targets}>"

    def get_link_targets(self, link_handle: str) -> List[str]:
        return [
            self._to_readable(t)
            for t in self._db.get_link_targets(self._to_md5(link_handle))
        ]

    def is_ordered(self, link_handle: str) -> bool:
        return self._db.is_ordered(self._to_md5(link_handle))

    def _translate_matches(self, matches) -> List[Any]:
        out = []
        for item in matches:
            if isinstance(item, str):
                out.append(self._to_readable(item))
            else:
                handle, targets = item
                out.append(
                    [
                        self._to_readable(handle),
                        [self._to_readable(t) for t in targets],
                    ]
                )
        return out

    def get_matched_links(self, link_type: str, target_handles: List[str]):
        return self._translate_matches(
            self._db.get_matched_links(
                link_type, [self._to_md5(t) for t in target_handles]
            )
        )

    def get_matched_type_template(self, template: List[Any]) -> List[Any]:
        return self._translate_matches(
            self._db.get_matched_type_template(template)
        )

    def get_matched_type(self, link_type: str) -> List[Any]:
        return self._translate_matches(self._db.get_matched_type(link_type))

    def get_all_nodes(self, node_type: str, names: bool = False) -> List[str]:
        if names:
            return self._db.get_all_nodes(node_type, names=True)
        return [
            self._to_readable(h) for h in self._db.get_all_nodes(node_type)
        ]

    def get_node_name(self, node_handle: str) -> str:
        return self._db.get_node_name(self._to_md5(node_handle))

    def get_matched_node_name(self, node_type: str, substring: str) -> List[str]:
        return [
            self._to_readable(h)
            for h in self._db.get_matched_node_name(node_type, substring)
        ]

    def get_atom_as_dict(self, handle: str, arity: int = -1) -> dict:
        return self._db.get_atom_as_dict(self._to_md5(handle), arity)

    def get_atom_as_deep_representation(self, handle: str, arity: int = -1):
        return self._db.get_atom_as_deep_representation(
            self._to_md5(handle), arity
        )

    def count_atoms(self) -> Tuple[int, int]:
        return self._db.count_atoms()
