"""Shim for /root/reference/das/logger.py (:3-43)."""

from das_tpu.utils.logger import logger  # noqa: F401
