"""Shim for /root/reference/das/exceptions.py (:3-22)."""

from das_tpu.core.exceptions import (  # noqa: F401
    AtomeseLexerError,
    AtomeseSyntaxError,
    MettaLexerError,
    MettaSyntaxError,
    UndefinedSymbolError,
)
