"""Shim for /root/reference/das/distributed_atom_space.py (:26-414).

`DistributedAtomSpace()` constructs against the TPU-native in-process
backends; `QueryOutputFormat` carries the same three members.  See
compat/das/__init__.py for the env-var mapping.
"""

from das_tpu.api.atomspace import (  # noqa: F401
    DistributedAtomSpace,
    QueryOutputFormat,
    Transaction,
)
from das_tpu.core.schema import WILDCARD  # noqa: F401  (reference :22 re-export)
