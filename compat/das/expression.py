"""Shim for /root/reference/das/expression.py (:6-56)."""

from das_tpu.core.expression import Expression  # noqa: F401
