#!/usr/bin/env bash
# One pre-commit/CI gate (ISSUE 12 satellite): the static analyzer with
# machine-readable SARIF output, then the lint + obs pytest markers —
# the two suites that pin the analyzer's registries (counters, env,
# FETCH_SITES, the DL014 span/metric names) and the observability
# layer's contracts (disabled-path no-op, exporter shapes).
#
#   ops/ci.sh [--changed-only]
#
# --changed-only passes through to ops/lint.sh (pre-commit fast path:
# changed das_tpu files + registry anchors under --allow-partial); the
# full run stays the CI authority.  SARIF lands in
# ${DASLINT_SARIF:-/tmp/daslint.sarif} for CI annotation upload; the
# human-readable text pass is what fails the gate (exit 1 on findings
# or stale baseline entries, exit 2 on usage errors).
set -euo pipefail
cd "$(dirname "$0")/.."

SARIF_OUT="${DASLINT_SARIF:-/tmp/daslint.sarif}"
CHANGED=()
if [ "${1:-}" = "--changed-only" ]; then
  CHANGED=(--changed-only)
  shift
fi

# 1. analyzer — the lint.sh text pass gates (compileall + analyzer +
#    doc-gen check); a direct analyzer invocation then records SARIF
#    (stdout must be PURE JSON — lint.sh's doc-gen check line would
#    corrupt it; the re-run is near-free on the analyzer's parse cache)
ops/lint.sh "${CHANGED[@]}" "$@"
python -m das_tpu.analysis das_tpu --format sarif > "$SARIF_OUT"
echo "daslint SARIF: $SARIF_OUT"

# 2. the registry-pinning + observability + robustness + profiling +
#    durability suites as one pytest run (lint: analyzer clean-tree pin
#    + per-rule fixture corpus; obs: span coverage, percentile math,
#    exporters, DL014; fault: chaos-parity sweep, deadlines, breaker
#    lifecycle, commit atomicity, DL015; prof: program-ledger
#    lifecycle, explain(compile=True), byte-model calibration,
#    bench_diff gate, DL016; dur: crash-point matrix over the persist
#    fault sites, torn-tail WAL truncation, corrupt-generation
#    fallback, warm-restore pins, DL017)
python -m pytest tests/ -q -m "lint or obs or fault or prof or dur"

# 3. the bench-history regression gate (ISSUE 14): the newest committed
#    record must pass against its own prior trajectory, proving the
#    parser reads every record and the committed history is
#    self-consistent — a fresh device record is gated the same way
#    before it lands
python scripts/bench_diff.py --self-check
