#!/usr/bin/env bash
# Black-box assertions against a RUNNING stack — role of the reference's
# scripts/service_regression_test.sh (string-compares CLI output incl.
# exact md5 handles).  Usage: ops/stack_smoke.sh [PORT]
set -euo pipefail
cd "$(dirname "$0")/.."
PORT="${1:-7025}"
CLI=(python -m das_tpu.service.client --port "$PORT")
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

fail() { echo "SMOKE FAIL: $1" >&2; exit 1; }

expect() { # expect <label> <want> <got>
  [ "$3" = "$2" ] || fail "$1: want '$2', got '$3'"
  echo "ok: $1 = $2"
}

NAME="smoke_$RANDOM"
TOKEN=$("${CLI[@]}" create "$NAME" | grep -oE '[a-z]{20}' | head -1)
[ -n "$TOKEN" ] || fail "create returned no token"
echo "ok: create -> token"

# the checkpoint volume pre-loads the animals KB: counts with ZERO load RPCs
expect "count (checkpoint attach)" "(14, 26)" "$("${CLI[@]}" count "$TOKEN")"

# exact-handle assertions (reference service_regression_test.sh:24-38)
expect "query human->mammal" \
  "{{'\$1': 'bdfe4e7a431f73386f37c6448afe5840'}}" \
  "$("${CLI[@]}" query "$TOKEN" "Node n1 Concept human, Link Inheritance n1 \$1")"

GOT=$("${CLI[@]}" atom "$TOKEN" af12f10f9ae2002a1607ba0b47ba8407 --output-format DICT)
case "$GOT" in
  *"'name': 'human'"*) echo "ok: get_atom human dict" ;;
  *) fail "get_atom: unexpected '$GOT'" ;;
esac

# load RPC round trip on a second tenant (file:// source + status poll)
python - <<'EOF'
import os
import sys
sys.path.insert(0, ".")
from das_tpu.models.animals import write_animals_metta
os.makedirs("/tmp/das_stack_smoke", exist_ok=True)
write_animals_metta("/tmp/das_stack_smoke/animals.metta")
EOF
NAME2="smoke2_$RANDOM"
TOKEN2=$("${CLI[@]}" create "$NAME2" | grep -oE '[a-z]{20}' | head -1)
"${CLI[@]}" load "$TOKEN2" "file:///tmp/das_stack_smoke/animals.metta" >/dev/null
for _ in $(seq 1 20); do
  S=$("${CLI[@]}" status "$TOKEN2")
  [ "$S" = "Ready" ] && break
  sleep 1
done
expect "load->status" "Ready" "$S"
expect "count (loaded)" "(14, 26)" "$("${CLI[@]}" count "$TOKEN2")"

echo "STACK SMOKE PASS (port $PORT)"
