#!/usr/bin/env bash
# Start the DAS service — role of the reference's scripts/server.sh +
# service-up.sh compose stack (no DB containers needed: the store is the
# in-process tensor backend).
set -euo pipefail
cd "$(dirname "$0")/.."
PORT="${1:-7025}"
BACKEND="${2:-tensor}"
exec python -m das_tpu.service.server --port "$PORT" --backend "$BACKEND"
