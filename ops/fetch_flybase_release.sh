#!/bin/bash
# Fetch a FlyBase release (SQL dump + precomputed report files) for the
# converter pipeline (das_tpu/convert/flybase.py --precomputed-dir).
# Role of the reference flybase2metta/fetch_flybase_release.sh.
set -euo pipefail

if [ "$#" -ne 2 ]; then
    echo "Usage: $0 <release tag> <target dir>"
    echo "   <release tag>  e.g. 2023_02"
    echo "   <target dir>   output directory (created if absent)"
    exit 1
fi

TAG="$1"
TARGET="$2"
BASE="https://ftp.flybase.net/releases/FB${TAG}"
PRECOMPUTED=(
    "fbgn_fbtr_fbpp_expanded_*.tsv.gz"
    "physical_interactions_mitab_fb_*.tsv.gz"
    "dmel_gene_sequence_ontology_annotations_fb_*.tsv.gz"
    "gene_map_table_*.tsv.gz"
    "ncRNA_genes_fb_*.json.gz"
    "gene_association.fb.gz"
    "gene_genetic_interactions_*.tsv.gz"
    "allele_genetic_interactions_*.tsv.gz"
    "allele_phenotypic_data_*.tsv.gz"
    "disease_model_annotations_fb_*.tsv.gz"
    "dmel_human_orthologs_disease_fb_*.tsv.gz"
    "fbrf_pmid_pmcid_doi_fb_*.tsv.gz"
)

mkdir -p "$TARGET/precomputed"

echo "Fetching SQL dump (FB${TAG})..."
wget -q -P "$TARGET" -r -np -nd -A "FB${TAG}.sql.gz" "${BASE}/psql/" || true
if ! compgen -G "$TARGET/FB${TAG}.sql.gz" > /dev/null; then
    # recursive wget exits 0 even when -A matched nothing: fetch directly
    # (to a temp name so a 404 never leaves a zero-byte stub behind)
    if wget -q -O "$TARGET/.sql.part" "${BASE}/psql/FB${TAG}.sql.gz"; then
        mv "$TARGET/.sql.part" "$TARGET/FB${TAG}.sql.gz"
    else
        rm -f "$TARGET/.sql.part"
    fi
fi
if ! compgen -G "$TARGET/FB${TAG}.sql.gz" > /dev/null; then
    echo "ERROR: SQL dump FB${TAG}.sql.gz not found under ${BASE}/psql/" >&2
    exit 2
fi

echo "Fetching precomputed report files..."
for pattern in "${PRECOMPUTED[@]}"; do
    wget -q -P "$TARGET/precomputed" -r -np -nd -A "$pattern" \
        "${BASE}/precomputed_files/" || true
    compgen -G "$TARGET/precomputed/${pattern}" > /dev/null \
        || echo "warn: no match for $pattern" >&2
done

echo "Decompressing..."
gunzip -f "$TARGET"/*.gz
gunzip -f "$TARGET"/precomputed/*.gz 2>/dev/null || true
echo "Done: $(ls "$TARGET" | wc -l) files in $TARGET, $(ls "$TARGET/precomputed" | wc -l) precomputed."
