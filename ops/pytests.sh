#!/usr/bin/env bash
# Test runner — role of the reference's scripts/pytests.sh (which had to
# reset Mongo/Redis containers and docker-load the KB first).  Here the
# store is in-process: the suite builds its KBs itself, and multi-chip
# behavior runs on a virtual 8-device CPU mesh (tests/conftest.py sets
# XLA_FLAGS=--xla_force_host_platform_device_count=8).
set -euo pipefail
cd "$(dirname "$0")/.."
# `ops/pytests.sh kernels` runs the Pallas kernel suite standalone — the
# intended loop on a TPU host, where the kernels compile (Mosaic) instead
# of interpreting; any further args pass through to pytest.
if [[ "${1:-}" == "kernels" ]]; then
  shift
  exec python -m pytest tests/ -q -m kernels "$@"
fi
# `ops/pytests.sh pipeline` runs the serving-pipeline + result-cache
# suite standalone (coalescer pipelining, cache invalidation pins).
if [[ "${1:-}" == "pipeline" ]]; then
  shift
  exec python -m pytest tests/ -q -m pipeline "$@"
fi
# `ops/pytests.sh sharded` runs the sharded serving-parity suite
# standalone (mesh dispatch/settle pipeline, sharded kernel routes,
# tree-composite + count-batch cache scope).
if [[ "${1:-}" == "sharded" ]]; then
  shift
  exec python -m pytest tests/ -q -m sharded "$@"
fi
# `ops/pytests.sh lint` runs the daslint static-analysis suite standalone
# (analyzer clean-run pin + per-rule fixture corpus); ops/lint.sh is the
# non-pytest wrapper for CI/pre-commit.
if [[ "${1:-}" == "lint" ]]; then
  shift
  exec python -m pytest tests/ -q -m lint "$@"
fi
# `ops/pytests.sh planner` runs the cost-based planner suite standalone
# (planner-vs-greedy bit-parity on the bio suite, retry-round-0 pins,
# estimator invalidation on commit, explain surface).
if [[ "${1:-}" == "planner" ]]; then
  shift
  exec python -m pytest tests/ -q -m planner "$@"
fi
# `ops/pytests.sh multiway` runs the k-way multiway join kernel suite
# standalone (kernel-vs-chain bit-parity incl. partial totals, the
# planner-routed bio/sharded end-to-end arms, the zero-retry acceptance
# pin, and the capacity-seed floor regression).
if [[ "${1:-}" == "multiway" ]]; then
  shift
  exec python -m pytest tests/ -q -m multiway "$@"
fi
# `ops/pytests.sh treefuse` runs the whole-tree fused execution suite
# standalone (fused-tree vs tree-executor bit-parity on the bio
# Or/negation suite, the one-program acceptance pin, fallback on
# composite shapes, fused-tree cache scope, sig distinctness).
if [[ "${1:-}" == "treefuse" ]]; then
  shift
  exec python -m pytest tests/ -q -m treefuse "$@"
fi
# `ops/pytests.sh obs` runs the observability suite standalone (trace
# span coverage for a coalesced query, cache/commit events, histogram
# percentile math, Perfetto/Prometheus exporter shapes, the
# disabled-mode no-op recorder pin, and the DL014 clean-tree pin).
if [[ "${1:-}" == "obs" ]]; then
  shift
  exec python -m pytest tests/ -q -m obs "$@"
fi
# `ops/pytests.sh fault` runs the dasfault robustness suite standalone
# (seeded chaos-parity sweep over FAULT_SITES on both backends, deadline
# expiry in queue/grouped/in-flight states, breaker trip/half-open/
# restore, RetryPolicy determinism, commit atomicity under injection,
# DL015 fixtures).
if [[ "${1:-}" == "fault" ]]; then
  shift
  exec python -m pytest tests/ -q -m fault "$@"
fi
# `ops/pytests.sh prof` runs the dasprof program-ledger suite standalone
# (ledger lifecycle on both backends, disabled-path identity pin,
# explain(compile=True) shape, byte-model calibration sanity, the
# bench_diff regression-gate unit cases, DL016 fixtures).
if [[ "${1:-}" == "prof" ]]; then
  shift
  exec python -m pytest tests/ -q -m prof "$@"
fi
# `ops/pytests.sh dur` runs the dasdur durability suite standalone
# (crash-point matrix over the five persist fault sites on both
# backends, torn-tail WAL truncation, corrupt-generation fallback,
# warm-bundle staleness + zero-retry warm restore, disabled-path
# identity, DL017 fixtures).
if [[ "${1:-}" == "dur" ]]; then
  shift
  exec python -m pytest tests/ -q -m dur "$@"
fi
python -m pytest tests/ -q "$@"
