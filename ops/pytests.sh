#!/usr/bin/env bash
# Test runner — role of the reference's scripts/pytests.sh (which had to
# reset Mongo/Redis containers and docker-load the KB first).  Here the
# store is in-process: the suite builds its KBs itself, and multi-chip
# behavior runs on a virtual 8-device CPU mesh (tests/conftest.py sets
# XLA_FLAGS=--xla_force_host_platform_device_count=8).
set -euo pipefail
cd "$(dirname "$0")/.."
python -m pytest tests/ -q "$@"
