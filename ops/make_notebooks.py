#!/usr/bin/env python
"""Generate the runnable-walkthrough notebooks with STORED outputs.

Role of /root/reference/notebooks/ (QueryDAS.ipynb, SimplePatternMiner.ipynb
ship with executed outputs — the de-facto baseline docs).  jupyter_client is
not in this image, so instead of a kernel each code cell is exec()'d in one
shared namespace with stdout captured and the trailing expression repr'd,
then written through nbformat as a v4 notebook whose outputs are the REAL
results of this run.

Usage:  JAX_PLATFORMS=cpu python ops/make_notebooks.py   (from the repo root)
"""

import ast
import contextlib
import io
import os
import sys

import nbformat

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "compat"))
sys.path.insert(0, REPO)


def run_cell(source: str, ns: dict):
    """Execute one cell REPL-style: exec the body, eval a trailing
    expression; returns (stdout_text, result_repr_or_None)."""
    tree = ast.parse(source)
    trailing = None
    if tree.body and isinstance(tree.body[-1], ast.Expr):
        trailing = ast.Expression(tree.body.pop(-1).value)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        if tree.body:
            exec(compile(tree, "<cell>", "exec"), ns)
        result = (
            eval(compile(trailing, "<cell>", "eval"), ns)
            if trailing is not None
            else None
        )
    return buf.getvalue(), (repr(result) if result is not None else None)


def build_notebook(cells, path):
    nb = nbformat.v4.new_notebook()
    ns: dict = {}
    count = 0
    for kind, source in cells:
        if kind == "md":
            nb.cells.append(nbformat.v4.new_markdown_cell(source))
            continue
        count += 1
        stdout, result = run_cell(source, ns)
        outputs = []
        if stdout:
            outputs.append(
                nbformat.v4.new_output("stream", name="stdout", text=stdout)
            )
        if result is not None:
            outputs.append(
                nbformat.v4.new_output(
                    "execute_result",
                    data={"text/plain": result},
                    execution_count=count,
                )
            )
        cell = nbformat.v4.new_code_cell(source, execution_count=count)
        cell.outputs = outputs
        nb.cells.append(cell)
    nbformat.write(nb, path)
    print(f"wrote {path} ({len(nb.cells)} cells)")


QUERY_DAS = [
    ("md", "# Query DAS after loading a knowledge base"),
    ("md",
     "This notebook mirrors the reference `notebooks/QueryDAS.ipynb` on the "
     "TPU-native backend: instantiate a `DistributedAtomSpace`, load the "
     "animals knowledge base, and run the four example queries.\n\n"
     "The imports come from the `das` compatibility package (`compat/das`), "
     "i.e. the exact module paths the reference uses — backed by das_tpu, "
     "with `matched()` routed through the device compiler."),
    ("code",
     "import sys\n"
     "sys.path.insert(0, '../compat'); sys.path.insert(0, '..')\n"
     "from das.distributed_atom_space import DistributedAtomSpace, QueryOutputFormat\n"
     "from das.pattern_matcher.pattern_matcher import PatternMatchingAnswer, "
     "OrderedAssignment, UnorderedAssignment, CompositeAssignment, "
     "Node, Link, Variable, Not, And, Or\n"
     "import warnings\n"
     "warnings.filterwarnings('ignore')\n"
     "das = DistributedAtomSpace(backend='tensor')\n"
     "das.load_knowledge_base('../data/samples/animals.metta')\n"
     "db = das.db\n"
     "db.prefetch()"),
    ("md",
     "Two utility functions showing how to iterate a query answer.  Answers "
     "mix `Ordered` assignments (one value per variable) and `Unordered` "
     "assignments (a multiset of values matching a multiset of variables)."),
    ("code",
     "def print_ordered_assignment(assignment):\n"
     "    if assignment is not None:\n"
     "        for key, value in assignment.mapping.items():\n"
     "            print(f\"{key}: {db.get_node_name(value)}\")\n"
     "\n"
     "def print_unordered_assignment(assignment):\n"
     "    if assignment is not None:\n"
     "        symbols = [s for s, c in assignment.symbols.items() for _ in range(c)]\n"
     "        values = [db.get_node_name(v) for v, c in assignment.values.items() for _ in range(c)]\n"
     "        print(f\"{', '.join(symbols)} = {', '.join(values)}\")"),
    ("md", "Print the atom count to make sure the knowledge base is correct."),
    ("code", "das.count_atoms()"),
    ("md",
     "The handle of `Concept:human` is reference-identical "
     "(md5 content addressing):"),
    ("code", "das.get_node('Concept', 'human')"),
    ("md",
     "Four example queries (`And` / `Or` / `Not` over `Link` patterns with "
     "`Variable`s — same constructors and keyword conventions as the "
     "reference)."),
    ("code",
     "V1 = Variable(\"V1\")\nV2 = Variable(\"V2\")\nV3 = Variable(\"V3\")\n"
     "my_query_1 = And([\n"
     "    Link(\"Inheritance\", ordered=True, targets=[V1, V2]),\n"
     "    Link(\"Inheritance\", ordered=True, targets=[V2, V3])\n"
     "])"),
    ("code",
     "N1 = Node(\"Concept\", \"human\")\n"
     "my_query_2 = And([\n"
     "    Link(\"Inheritance\", ordered=True, targets=[V1, V2]),\n"
     "    Link(\"Inheritance\", ordered=True, targets=[V2, V3]),\n"
     "    Not(Link(\"Inheritance\", ordered=True, targets=[N1, V2]))\n"
     "])"),
    ("code",
     "N2 = Node(\"Concept\", \"snake\")\n"
     "my_query_3 = And([\n"
     "    Link(\"Inheritance\", ordered=True, targets=[V1, V2]),\n"
     "    Link(\"Inheritance\", ordered=True, targets=[V2, V3]),\n"
     "    Not(Or([\n"
     "        Link(\"Inheritance\", ordered=True, targets=[N1, V2]),\n"
     "        Link(\"Inheritance\", ordered=True, targets=[N2, V2])\n"
     "    ]))\n"
     "])"),
    ("code",
     "NM = Node(\"Concept\", \"mammal\")\n"
     "my_query_4 = And([\n"
     "    Link(\"Similarity\", ordered=False, targets=[V1, V2]),\n"
     "    Not(Or([\n"
     "        Link(\"Inheritance\", ordered=True, targets=[V1, NM]),\n"
     "        Link(\"Inheritance\", ordered=True, targets=[V2, NM]),\n"
     "    ]))\n"
     "])"),
    ("md",
     "Execute each query.  `matched()` routes through the compiled device "
     "path (fused / tree executor) and falls back to the host algebra only "
     "outside the compilable language; either way the answer sets are "
     "reference-identical."),
    ("code",
     "for name, q in [(\"my_query_1\", my_query_1), (\"my_query_2\", my_query_2),\n"
     "                (\"my_query_3\", my_query_3), (\"my_query_4\", my_query_4)]:\n"
     "    query_answer = PatternMatchingAnswer()\n"
     "    matched = q.matched(db, query_answer)\n"
     "    print(f\"{name}: matched={matched}, \"\n"
     "          f\"{len(query_answer.assignments)} assignments\")"),
    ("md", "Inspect one answer set in full (query 4: similar non-mammals)."),
    ("code",
     "query_answer = PatternMatchingAnswer()\n"
     "matched = my_query_4.matched(db, query_answer)\n"
     "for assignment in sorted(query_answer.assignments):\n"
     "    if type(assignment) is OrderedAssignment:\n"
     "        print_ordered_assignment(assignment)\n"
     "    elif type(assignment) is UnorderedAssignment:\n"
     "        print_unordered_assignment(assignment)\n"
     "    elif type(assignment) is CompositeAssignment:\n"
     "        print_ordered_assignment(assignment.ordered_mapping)\n"
     "        for unordered_assignment in assignment.unordered_mappings:\n"
     "            print_unordered_assignment(unordered_assignment)\n"
     "    print(\"\")"),
    ("md",
     "The same queries are also available through the API facade with "
     "formatted output:"),
    ("code",
     "print(das.query(my_query_1, QueryOutputFormat.HANDLE)[:300] + ' ...')"),
]


SIMPLE_PATTERN_MINER = [
    ("md", "# Simple Pattern Miner"),
    ("md",
     "TPU-native edition of the reference `SimplePatternMiner.ipynb`: mine "
     "surprising conjunctive patterns from a bio atomspace.  The reference "
     "notebook's stored baseline is **74-104 ms per halo link** for its "
     "template-build + count loop against a live Redis cluster (cell 9); "
     "here candidate counting funnels through batched device count "
     "programs (`query/fused.py count_batch`)."),
    ("code",
     "import sys, time\n"
     "sys.path.insert(0, '..')\n"
     "import warnings; warnings.filterwarnings('ignore')\n"
     "from das_tpu.models.bio import build_bio_ontology_atomspace\n"
     "from das_tpu.storage.tensor_db import TensorDB\n"
     "from das_tpu.core.config import DasConfig\n"
     "from das_tpu.mining.miner import PatternMiner\n"
     "data, _, _ = build_bio_ontology_atomspace(\n"
     "    n_genes=20000, n_processes=2000, members_per_gene=5,\n"
     "    n_interactions=40000, n_reactomes=2000, n_uniprots=6000)\n"
     "db = TensorDB(data, DasConfig())\n"
     "db.prefetch()"),
    ("md", "Atom counts for this run (the reference's cell 0 prints its "
     "FlyBase store: `(2584508, 27871440)`; bench.py's flybase section "
     "measures that scale on real hardware):"),
    ("code", "db.count_atoms()"),
    ("md",
     "**Halo expansion** — all links within 2 hops of three seed genes.  "
     "The reference probes 5 wildcard templates per node per level "
     "(~0.1 ms per warm Redis probe); here the incoming-set CSR lives on "
     "device, so the halo is an offsets gather per frontier."),
    ("code",
     "miner = PatternMiner(db, halo_length=2, link_rate=0.01, seed=7)\n"
     "genes = db.get_all_nodes('Gene', names=True)[:3]\n"
     "gene_handles = [db.get_node_handle('Gene', g) for g in genes]\n"
     "t0 = time.perf_counter()\n"
     "universe = miner.expand_halo(gene_handles)\n"
     "halo_s = time.perf_counter() - t0\n"
     "print(f'{universe} halo links in {halo_s*1e3:.0f} ms')"),
    ("md",
     "**Candidate patterns** — every wildcard variant of every halo link, "
     "counted in batched device programs (the reference runs one Redis "
     "round trip per candidate)."),
    ("code",
     "t0 = time.perf_counter()\n"
     "n_candidates = miner.build_patterns()\n"
     "count_s = time.perf_counter() - t0\n"
     "print(f'{n_candidates} candidate patterns counted in {count_s:.1f} s')"),
    ("md",
     "**Mining loop** — sample 3-term composite patterns, count their "
     "joint matches, score by I-Surprisingness (observed probability vs "
     "the best independence estimate over every binary partition)."),
    ("code",
     "t0 = time.perf_counter()\n"
     "best = miner.mine(ngram=3, epochs=50)\n"
     "mine_s = time.perf_counter() - t0\n"
     "print(f'joint mining {mine_s:.1f} s')\n"
     "print(f'best pattern count={best.count} "
     "isurprisingness={best.isurprisingness:.4f}')\n"
     "for term in best.term_handles:\n"
     "    print('  ', term)"),
    ("md", "Throughput summary vs the reference baseline:"),
    ("code",
     "total_s = halo_s + count_s + mine_s\n"
     "print(f'counting phase: {(halo_s+count_s)/universe*1e3:.2f} ms/link '\n"
     "      f'(reference loop: 74-104 ms/link)')\n"
     "print(f'total incl. whole-KB ngram joint mining: '\n"
     "      f'{total_s/universe*1e3:.2f} ms/link')"),
]


LOAD_KNOWLEDGE_BASE = [
    ("md", "# Load a knowledge base"),
    ("md",
     "TPU-native edition of the reference `LoadKnowledgeBase.ipynb`: the "
     "three load paths — the general MeTTa parser, the canonical fast "
     "path (C++ scanner when built), and incremental transaction "
     "commits."),
    ("code",
     "import sys\n"
     "sys.path.insert(0, '../compat'); sys.path.insert(0, '..')\n"
     "import warnings; warnings.filterwarnings('ignore')\n"
     "from das.distributed_atom_space import DistributedAtomSpace\n"
     "das = DistributedAtomSpace(backend='tensor')"),
    ("md", "**General parser path** — any .metta/.scm file or directory:"),
    ("code",
     "das.load_knowledge_base('../data/samples/animals.metta')\n"
     "das.count_atoms()"),
    ("md",
     "**Canonical fast path** — normalized one-expression-per-line files "
     "(converter output).  The native C++ scanner parses GIL-free with "
     "inline md5; identical records to the Python scanner:"),
    ("code",
     "from das_tpu.ingest import native\n"
     "from das_tpu.models.bio import write_bio_canonical\n"
     "import tempfile, os, time\n"
     "d = tempfile.mkdtemp()\n"
     "path = os.path.join(d, 'bio.metta')\n"
     "lines = write_bio_canonical(path, n_genes=5000, n_processes=500,\n"
     "                            members_per_gene=5, n_interactions=4000)\n"
     "das2 = DistributedAtomSpace(backend='tensor')\n"
     "t0 = time.perf_counter()\n"
     "das2.load_canonical_knowledge_base(path)\n"
     "dt = time.perf_counter() - t0\n"
     "print(f'native scanner: {native.native_available()}')\n"
     "print(f'{lines} expressions in {dt:.2f}s '\n"
     "      f'({os.path.getsize(path)/1e6/dt:.1f} MB/s)')\n"
     "das2.count_atoms()"),
    ("md",
     "**Incremental commits** — O(delta) device-side merge, no "
     "re-finalize (the reference's das_update_test.py path):"),
    ("code",
     "tx = das.open_transaction()\n"
     "tx.add('(: \"dog\" Concept)')\n"
     "tx.add('(Inheritance \"dog\" \"mammal\")')\n"
     "das.commit_transaction(tx)\n"
     "das.count_atoms()"),
    ("code",
     "das.get_node('Concept', 'dog')"),
]


QUERY_FLYBASE = [
    ("md", "# Query a FlyBase-style knowledge base"),
    ("md",
     "TPU-native edition of the reference `QueryFlyBase.ipynb`: convert a "
     "PostgreSQL dump with the FlyBase converter, load the emitted MeTTa, "
     "and run Execution-link queries with wall-clock timing."),
    ("code",
     "import sys, glob, time\n"
     "sys.path.insert(0, '../compat'); sys.path.insert(0, '..')\n"
     "import warnings; warnings.filterwarnings('ignore')\n"
     "import tempfile, os\n"
     "from das_tpu.convert.flybase import FlybaseConverter\n"
     "d = tempfile.mkdtemp()\n"
     "sql = os.path.join(d, 'dump.sql')\n"
     "with open(sql, 'w') as f:\n"
     "    f.write('CREATE TABLE public.gene (\\n'\n"
     "            '    gene_id integer NOT NULL,\\n'\n"
     "            '    name text,\\n'\n"
     "            '    organism_id integer\\n'\n"
     "            ');\\n'\n"
     "            'CREATE TABLE public.organism (\\n'\n"
     "            '    organism_id integer NOT NULL,\\n'\n"
     "            '    genus text\\n'\n"
     "            ');\\n'\n"
     "            'COPY public.gene (gene_id, name, organism_id) FROM stdin;\\n'\n"
     "            + ''.join(f'{i}\\tFBgn{i:07d}\\t{1 + i % 3}\\n' for i in range(200))\n"
     "            + '\\\\.\\n'\n"
     "            'COPY public.organism (organism_id, genus) FROM stdin;\\n'\n"
     "            '1\\tDrosophila\\n2\\tMusca\\n3\\tAedes\\n'\n"
     "            '\\\\.\\n'\n"
     "            'ALTER TABLE ONLY public.gene ADD CONSTRAINT g_pk PRIMARY KEY (gene_id);\\n'\n"
     "            'ALTER TABLE ONLY public.organism ADD CONSTRAINT o_pk PRIMARY KEY (organism_id);\\n'\n"
     "            'ALTER TABLE ONLY public.gene ADD CONSTRAINT g_fk FOREIGN KEY (organism_id) '\n"
     "            'REFERENCES public.organism(organism_id);\\n')\n"
     "out = os.path.join(d, 'metta')\n"
     "FlybaseConverter(sql, out).run()"),
    ("md", "Load the converted files (reference loads its file_NNN.metta "
     "chunks the same way):"),
    ("code",
     "from das.distributed_atom_space import DistributedAtomSpace\n"
     "das = DistributedAtomSpace(backend='tensor')\n"
     "for p in sorted(glob.glob(out + '/*.metta')):\n"
     "    das.load_knowledge_base(p)\n"
     "das.count_atoms()"),
    ("md",
     "Execution-link query with wall-clock timing (the reference's "
     "WallClock cells): which genes belong to organism 1?"),
    ("code",
     "from das.pattern_matcher.pattern_matcher import (\n"
     "    And, Link, Node, PatternMatchingAnswer, Variable)\n"
     "q = Link('Execution', ordered=True, targets=[\n"
     "    Link('Schema', ordered=True, targets=[Node('Schema', 'gene.organism_id')]),\n"
     "    Variable('V_gene'),\n"
     "    Node('Concept', 'organism:1'),\n"
     "])\n"
     "answer = PatternMatchingAnswer()\n"
     "t0 = time.perf_counter()\n"
     "matched = q.matched(das.db, answer)\n"
     "dt = (time.perf_counter() - t0) * 1000\n"
     "print(f'{len(answer.assignments)} genes in {dt:.1f} ms')"),
    ("md", "Resolve a few of the answers to node names:"),
    ("code",
     "names = sorted(das.db.get_node_name(list(a.mapping.values())[0])\n"
     "               for a in answer.assignments)\n"
     "print(names[:10])"),
]


if __name__ == "__main__":
    out_dir = os.path.join(REPO, "notebooks")
    os.makedirs(out_dir, exist_ok=True)
    os.chdir(out_dir)  # notebooks use ../ relative paths
    only = sys.argv[1:] or ["QueryDAS", "SimplePatternMiner",
                            "LoadKnowledgeBase", "QueryFlyBase"]
    specs = {
        "QueryDAS": QUERY_DAS,
        "SimplePatternMiner": SIMPLE_PATTERN_MINER,
        "LoadKnowledgeBase": LOAD_KNOWLEDGE_BASE,
        "QueryFlyBase": QUERY_FLYBASE,
    }
    for name in only:
        build_notebook(specs[name], os.path.join(out_dir, f"{name}.ipynb"))
