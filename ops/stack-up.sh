#!/usr/bin/env bash
# ONE command: build, seed the checkpoint volume, start the service, and
# smoke-test it — the reference's docker-compose-service.yml +
# run-instance-deployment.sh analogue (VERDICT r03 missing #2).
#
#   ops/stack-up.sh                 # docker compose when available,
#                                   # process-mode stack otherwise
#   ops/stack-up.sh --down          # stop either form
#
# Docker mode:   compose.yml (seed one-shot -> das-service on the
#                das-checkpoint volume), then stack_smoke.sh against it.
# Process mode:  same seed + same service + same smoke, as local
#                processes on $DAS_STACK_DIR (default /tmp/das_stack) —
#                used on hosts without a container runtime (CI, TPU VMs
#                with bare metal runtimes).
set -euo pipefail
cd "$(dirname "$0")/.."

PORT="${DAS_STACK_PORT:-7025}"
STACK_DIR="${DAS_STACK_DIR:-/tmp/das_stack}"
PIDFILE="$STACK_DIR/service.pid"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

have_compose() {
  command -v docker >/dev/null 2>&1 && docker compose version >/dev/null 2>&1
}

if [ "${1:-}" = "--down" ]; then
  if have_compose; then
    docker compose -f ops/compose.yml down
  fi
  if [ -f "$PIDFILE" ]; then
    kill "$(cat "$PIDFILE")" 2>/dev/null || true
    rm -f "$PIDFILE"
    echo "process-mode stack stopped"
  fi
  exit 0
fi

if have_compose; then
  docker compose -f ops/compose.yml up -d --build
  echo "waiting for the service on :$PORT ..."
  for _ in $(seq 1 60); do
    if python -m das_tpu.service.client --port "$PORT" create "probe_$RANDOM" \
        >/dev/null 2>&1; then
      break
    fi
    sleep 2
  done
  ops/stack_smoke.sh "$PORT"
  exit 0
fi

echo "no container runtime: process-mode stack in $STACK_DIR"
mkdir -p "$STACK_DIR"
make -C native >/dev/null

# seed the checkpoint "volume" (idempotent)
python -m das_tpu.service.seed_checkpoint "$STACK_DIR/kb"

# start the service bound to the checkpoint
if [ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null; then
  echo "service already running (pid $(cat "$PIDFILE"))"
else
  DAS_TPU_CHECKPOINT="$STACK_DIR/kb" nohup python -m das_tpu.service.server \
    --port "$PORT" --backend tensor > "$STACK_DIR/service.log" 2>&1 &
  echo $! > "$PIDFILE"
  echo "service starting (pid $(cat "$PIDFILE"), log $STACK_DIR/service.log)"
fi

for _ in $(seq 1 60); do
  if python -m das_tpu.service.client --port "$PORT" create "probe_$RANDOM" \
      >/dev/null 2>&1; then
    break
  fi
  sleep 1
done

ops/stack_smoke.sh "$PORT"
echo "stack is up on :$PORT (ops/stack-up.sh --down to stop)"
