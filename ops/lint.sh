#!/usr/bin/env bash
# Static gate: daslint (the AST invariant analyzer, ARCHITECTURE.md §11)
# + a bytecode compile of the whole package + the generated-docs check.
# Run from anywhere; pass extra args through to the analyzer
# (e.g. ops/lint.sh --select DL003 --format json).
#
# --changed-only (first arg): pre-commit fast path — analyze only the
# das_tpu/*.py files changed vs HEAD (staged, unstaged, untracked),
# plus the registry-bearing modules every cross-file rule anchors on
# (counters, ENV_REGISTRY, KERNEL_BUFFERS, COLLECTIVE_SITES,
# FETCH_SITES, LOCK_DISCIPLINE), under --allow-partial so staleness
# legs that need the full tree don't fire on the subset.  The full run
# stays the authority; CI runs it.
set -euo pipefail
cd "$(dirname "$0")/.."

if [ "${1:-}" = "--changed-only" ]; then
  shift
  mapfile -t changed < <(
    {
      git diff --name-only HEAD -- 'das_tpu/*.py' 'das_tpu/**/*.py'
      git ls-files --others --exclude-standard -- 'das_tpu/*.py' 'das_tpu/**/*.py'
    } | sort -u
  )
  if [ "${#changed[@]}" -eq 0 ]; then
    echo "daslint: no changed das_tpu/*.py files — skipping analyzer"
    exit 0
  fi
  # registry anchors: cross-file rules resolve their declared sets here
  anchors=(
    das_tpu/ops/counters.py
    das_tpu/core/config.py
    das_tpu/kernels/budget.py
    das_tpu/parallel/mesh.py
    das_tpu/service/coalesce.py
    das_tpu/query/fused.py
  )
  files=()
  for f in "${changed[@]}" "${anchors[@]}"; do
    [ -f "$f" ] || continue
    case " ${files[*]-} " in *" $f "*) ;; *) files+=("$f") ;; esac
  done
  python -m compileall -q "${files[@]}"
  python -m das_tpu.analysis "${files[@]}" --allow-partial "$@"
  exit 0
fi

python -m compileall -q das_tpu
python -m das_tpu.analysis das_tpu "$@"
python scripts/gen_env_table.py --check
