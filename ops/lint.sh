#!/usr/bin/env bash
# Static gate: daslint (the AST invariant analyzer, ARCHITECTURE.md §11)
# + a bytecode compile of the whole package + the generated-docs check.
# Run from anywhere; pass extra args through to the analyzer
# (e.g. ops/lint.sh --rules DL003 --json).
set -euo pipefail
cd "$(dirname "$0")/.."
python -m compileall -q das_tpu
python -m das_tpu.analysis das_tpu "$@"
python scripts/gen_env_table.py --check
