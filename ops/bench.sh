#!/usr/bin/env bash
# Run the headline benchmark (bench.py prints one JSON line) plus the
# 3-layout harness — role of the reference's scripts/benchmark.py loop.
set -euo pipefail
cd "$(dirname "$0")/.."
python bench.py
python scripts/benchmark.py --rounds "${1:-10}"
