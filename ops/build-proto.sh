#!/bin/bash
# Regenerate das_pb2.py from the carried proto contract (role of
# /root/reference/service/build-proto.sh:3; grpc_tools is unavailable in
# this image, so messages come from protoc and the grpc stubs are the
# hand-written service_spec/das_pb2_grpc.py).
set -euo pipefail
cd "$(dirname "$0")/../das_tpu/service/service_spec"
protoc -I. --python_out=. das.proto
echo "regenerated $(pwd)/das_pb2.py"
