#!/usr/bin/env bash
# Black-box service smoke test — role of the reference's
# scripts/service_regression_test.sh (drives the RPC surface and asserts
# exact md5 handles, e.g. Concept:human = af12f10f9ae2002a1607ba0b47ba8407,
# and count == (14, 26)).  The assertions live in tests/test_service.py.
set -euo pipefail
cd "$(dirname "$0")/.."
python -m pytest tests/test_service.py -q "$@"
