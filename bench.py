#!/usr/bin/env python
"""Benchmark: 3-variable conjunctive pattern matching on a bio-scale KB.

North-star metric (BASELINE.json): pattern-matches/sec + p50 query latency
for 3-var conjunctive queries over a bio atomspace, identical result sets.

Query (both engines, same data): "genes in a shared biological process
that also interact" — And(Member(V1,V3), Member(V2,V3), Interacts(V1,V2)).

Two measurements:
  * headline `value` — device p50 latency for the query on the BIO-SCALE
    KB (the reference execution model cannot complete this size: its
    nested-loop join is O(|A|x|B|) Python objects);
  * `vs_baseline` — measured head-to-head at a smaller config where the
    reference execution model (single-threaded Python assignment algebra,
    differentially verified against upstream in tests/test_differential.py)
    finishes: identical result sets asserted, ratio of wall times.  The
    baseline runs on an in-memory store, i.e. WITHOUT the reference's
    0.1 ms/probe Redis round-trips (SimplePatternMiner.ipynb stored
    output), so the ratio is conservative.

Prints ONE JSON line.
"""

import json
import statistics
import sys
import time

sys.path.insert(0, ".")

import das_tpu  # noqa: F401  (enables x64)
import jax

from das_tpu.core.config import DasConfig
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.query import compiler
from das_tpu.query.ast import And, Link, PatternMatchingAnswer, Variable
from das_tpu.storage.memory_db import MemoryDB
from das_tpu.storage.tensor_db import TensorDB

import os

_SCALE = float(os.environ.get("DAS_BENCH_SCALE", "1"))
LARGE = dict(n_genes=int(20000 * _SCALE), n_processes=max(20, int(2000 * _SCALE)),
             members_per_gene=5, n_interactions=int(15000 * _SCALE),
             n_evaluations=int(5000 * _SCALE))
SMALL = dict(n_genes=300, n_processes=30, members_per_gene=5,
             n_interactions=300, n_evaluations=0)
ROUNDS = int(os.environ.get("DAS_BENCH_ROUNDS", "30"))


def three_var_query():
    return And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Variable("V1"), Variable("V2")], True),
    ])


def device_p50(dev_db, rounds=ROUNDS):
    q = three_var_query()
    compiler.count_matches(dev_db, q)  # warm compile cache
    times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        compiler.count_matches(dev_db, q)
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def main():
    # --- head-to-head at reference-feasible scale -------------------------
    sdata, _, _ = build_bio_atomspace(**SMALL)
    host_db = MemoryDB(sdata)
    sdev_db = TensorDB(sdata, DasConfig())
    a_host = PatternMatchingAnswer()
    t0 = time.perf_counter()
    three_var_query().matched(host_db, a_host)
    baseline_s = time.perf_counter() - t0
    a_dev = PatternMatchingAnswer()
    compiler.query_on_device(sdev_db, three_var_query(), a_dev)
    assert a_dev.assignments == a_host.assignments, "result sets diverged"
    small_matches = len(a_host.assignments)
    small_device_s = device_p50(sdev_db, rounds=10)
    vs_baseline = baseline_s / small_device_s if small_device_s > 0 else 0.0

    # --- headline: bio-scale KB, device only ------------------------------
    t0 = time.perf_counter()
    ldata, _, _ = build_bio_atomspace(**LARGE)
    build_s = time.perf_counter() - t0
    nodes, links = ldata.count_atoms()
    dev_db = TensorDB(ldata, DasConfig(initial_result_capacity=1 << 16))
    n_matches = compiler.count_matches(dev_db, three_var_query())
    p50 = device_p50(dev_db)
    matches_per_sec = n_matches / p50 if p50 > 0 else 0.0

    print(json.dumps({
        "metric": "bio_atomspace 3-var conjunctive query p50 latency (device)",
        "value": round(p50 * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(vs_baseline, 1),
        "extra": {
            "platform": jax.devices()[0].platform,
            "device": str(jax.devices()[0]),
            "workload": LARGE,       # cross-run comparability (ADVICE r1)
            "rounds": ROUNDS,
            "kb_nodes": nodes,
            "kb_links": links,
            "kb_build_s": round(build_s, 2),
            "matches": n_matches,
            "pattern_matches_per_sec": round(matches_per_sec),
            "baseline_config": SMALL,
            "baseline_s": round(baseline_s, 3),
            "baseline_matches": small_matches,
            "small_device_p50_ms": round(small_device_s * 1e3, 3),
            "baseline_model": "reference Python algebra on in-memory store",
        },
    }))


if __name__ == "__main__":
    main()
