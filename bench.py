#!/usr/bin/env python
"""Benchmark: 3-variable conjunctive pattern matching on a bio-scale KB.

North-star metric (BASELINE.json): pattern-matches/sec + p50 query latency
for 3-var conjunctive queries over a bio atomspace, identical result sets.

Query (both engines, same data): "genes in a shared biological process
that also interact" — And(Member(V1,V3), Member(V2,V3), Interacts(V1,V2)).

Two measurements:
  * headline `value` — device p50 latency for the query on the BIO-SCALE
    KB (the reference execution model cannot complete this size: its
    nested-loop join is O(|A|x|B|) Python objects);
  * `vs_baseline` — measured head-to-head at a smaller config where the
    reference execution model (single-threaded Python assignment algebra,
    differentially verified against upstream in tests/test_differential.py)
    finishes: identical result sets asserted, ratio of wall times.  The
    baseline runs on an in-memory store, i.e. WITHOUT the reference's
    0.1 ms/probe Redis round-trips (SimplePatternMiner.ipynb stored
    output), so the ratio is conservative.

Prints ONE JSON line.
"""

import json
import os
import statistics
import sys
import time

_START = time.time()

sys.path.insert(0, ".")

import das_tpu  # noqa: F401  (enables x64)
import jax

from das_tpu.obs import proflog


def _enable_proflog():
    """Program ledger ON for the bench run (ISSUE 14): every section's
    record carries programs_compiled + compile_s, and the full record
    ends with the ledger snapshot — the bench finally reports what the
    compiles COST, not just how many were avoided.  An explicit
    DAS_TPU_PROFLOG=0 still wins (the env is authoritative for
    operators), and this runs from the entry points, never at import —
    importing bench (test_bench_contract) must not flip a process-wide
    switch."""
    if os.environ.get("DAS_TPU_PROFLOG") is None:
        proflog.configure(enabled=True)


def _with_programs(section_fn, *args, **kwargs):
    """Run one bench section and fold the ledger's compile delta into
    its record: `programs_compiled` (XLA compiles the section paid) and
    `compile_s` (wall seconds they took).  Sections that raise keep
    their error-record shape — the wrapper only decorates dict results."""
    before = proflog.compile_totals()
    out = section_fn(*args, **kwargs)
    if isinstance(out, dict):
        out.update(proflog.compile_delta(before))
    return out

from das_tpu.core.config import DasConfig
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.query import compiler
from das_tpu.query.ast import (
    And,
    Link,
    Node,
    Not,
    Or,
    PatternMatchingAnswer,
    Variable,
)
from das_tpu.storage.memory_db import MemoryDB
from das_tpu.storage.tensor_db import TensorDB

import os

# whole-run wall-clock budget (VERDICT r03 item 1): the flybase section is
# scaled to whatever remains after the main section, and is skipped (with
# an "error" note, never a dead process) when nothing useful remains —
# r03's driver run timed out with the headline unprinted
BUDGET_S = float(os.environ.get("DAS_BENCH_BUDGET_S", "2700"))


def budget_remaining() -> float:
    """Seconds left.  A child process inherits the parent's absolute
    deadline via DAS_BENCH_DEADLINE (its own _START would reset the
    clock)."""
    deadline = os.environ.get("DAS_BENCH_DEADLINE")
    if deadline:
        return float(deadline) - time.time()
    return BUDGET_S - (time.time() - _START)


_SCALE = float(os.environ.get("DAS_BENCH_SCALE", "1"))
LARGE = dict(n_genes=int(20000 * _SCALE), n_processes=max(20, int(2000 * _SCALE)),
             members_per_gene=5, n_interactions=int(15000 * _SCALE),
             n_evaluations=int(5000 * _SCALE))
SMALL = dict(n_genes=300, n_processes=30, members_per_gene=5,
             n_interactions=300, n_evaluations=0)
ROUNDS = int(os.environ.get("DAS_BENCH_ROUNDS", "30"))

# the reference baseline KB: 2,584,508 nodes / 27,871,440 links
# (SimplePatternMiner.ipynb cell 0; BASELINE.md row 1).  This config lands
# within ~1% of both: nodes = genes + processes + predicate + concepts;
# links = 10/gene Member + 2x interactions Interacts + 2x evaluations.
FLYBASE = dict(n_genes=2_400_000, n_processes=180_000, members_per_gene=10,
               n_interactions=1_500_000, n_evaluations=435_000)


def three_var_query():
    return And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Variable("V1"), Variable("V2")], True),
    ])


def host_visible_p50(dev_db, rounds=ROUNDS):
    """Host-to-host latency of one count query — includes every transport
    round trip (the tunnel RTT on remote TPUs).  This was the r01/r02
    headline; r03 reports it alongside the transport decomposition below
    so the rounds reconcile."""
    q = three_var_query()
    compiler.count_matches(dev_db, q)  # warm compile cache
    times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        compiler.count_matches(dev_db, q)
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def transport_rtt_ms(rounds=10):
    """One host<->device round trip: dispatch a trivial jitted op on a
    resident array and fetch its 1-element result.  On a tunneled TPU this
    is the per-fetch latency floor every host-visible number contains."""
    import numpy as np

    x = jax.device_put(jax.numpy.zeros((8,), dtype=jax.numpy.int32))
    tick = jax.jit(lambda v, i: (v + i).sum())
    np.asarray(tick(x, 1))  # warm compile
    times = []
    for i in range(rounds):
        t0 = time.perf_counter()
        np.asarray(tick(x, i))
        times.append(time.perf_counter() - t0)
    return statistics.median(times) * 1e3


def fetches_per_query(dev_db, q=None):
    """How many device fetches (each a full RTT through a tunnel) one
    sequential count query performs.  FETCH_COUNTS instruments the fused
    executor only; a query that declined to a path we don't instrument
    reports None rather than pretending it made zero round trips.
    Callers on KBs where the all-variable query legitimately exceeds the
    capacity ceiling (the 27.9M-link flybase store: Member x Member alone
    is ~3.2e9 rows) pass a query from their own workload instead."""
    from das_tpu.query import fused

    q = q if q is not None else three_var_query()
    compiler.count_matches(dev_db, q)  # warm
    before = fused.FETCH_COUNTS["n"]
    compiler.count_matches(dev_db, q)
    delta = fused.FETCH_COUNTS["n"] - before
    return delta if delta > 0 else None


def _best_of(fn, rounds):
    times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return min(times)


def device_only_ms(dev_db, plans_list_of, w1=32, w2=256, rounds=5):
    """Per-query DEVICE latency with transport excluded, tiered:

    1. "loop": two fori_loop count programs of widths W1/W2 (ONE dispatch
       + ONE fetch each, so fixed transport cost cancels in the width
       slope) — true SEQUENTIAL per-query device latency;
    2. "batched_slope": when the loop program cannot compile on the
       backend (a TPU scoped-vmem ceiling has been observed for the
       loop-fused body), the width slope of the vmapped count_batch
       programs — per-query device compute in the batched regime, using
       executables already proven on this backend;
    fall through to the caller's subtraction estimate otherwise.
    Returns (ms, method)."""
    from das_tpu.query.fused import get_executor

    ex = get_executor(dev_db)
    plans1, plans2 = plans_list_of(w1), plans_list_of(w2)
    # a small KB may not have w2 distinct queries: use the REAL widths in
    # the slope, never the nominal ones
    w1, w2 = len(plans1), len(plans2)
    if w2 <= w1:
        raise ValueError(f"need two distinct widths, got {w1}/{w2}")
    try:
        run1, _ = ex.build_count_loop(plans1)
        run2, _ = ex.build_count_loop(plans2)
        t1, t2 = _best_of(run1, rounds), _best_of(run2, rounds)
        slope = (t2 - t1) / (w2 - w1)
        if slope <= 0:  # clock noise swamped the width delta: report the
            slope = t2 / w2  # amortized upper bound instead of a negative
        return slope * 1e3, "loop"
    except Exception as e:
        print(f"[bench] sequential loop unavailable: {e!r}", file=sys.stderr)
    counts = ex.count_batch(plans2)  # warm compile + caps at larger width
    if any(c is None for c in counts):
        # the batch declined lanes: its wall time would measure host-side
        # prep, not device compute — let the caller's subtraction handle it
        raise RuntimeError("count_batch declined lanes; no batched slope")
    ex.count_batch(plans1)
    t1 = _best_of(lambda: ex.count_batch(plans1), rounds)
    t2 = _best_of(lambda: ex.count_batch(plans2), rounds)
    slope = (t2 - t1) / (w2 - w1)
    if slope <= 0:
        slope = t2 / w2
    return slope * 1e3, "batched_slope"


def grounded_query(gene_name):
    """3-clause conjunctive query with shared variables, grounded on one
    gene: processes of G, plus same-process genes interacting with G."""
    return And([
        Link("Member", [Node("Gene", gene_name), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Node("Gene", gene_name), Variable("V2")], True),
    ])


def batched_per_query(dev_db, width=None, rounds=5, verify=True):
    """Per-query latency at batch width: W distinct grounded queries counted
    in one vmapped dispatch group (query/fused.py count_batch).  This is the
    serving-shaped measurement — the reference's per-probe budget
    (0.097-0.131 ms warm Redis, SimplePatternMiner.ipynb cell 6) is likewise
    a warm amortized figure.  Every separate host sync on a tunneled TPU is
    a full RTT, so batch width is the honest way to amortize it."""
    from das_tpu.query.fused import get_executor

    width = width or int(os.environ.get("DAS_BENCH_BATCH", "256"))
    genes = dev_db.get_all_nodes("Gene", names=True)[:width]
    if len(genes) < width:
        width = len(genes)
    plans = [compiler.plan_query(dev_db, grounded_query(g)) for g in genes]
    assert all(p is not None for p in plans), "grounded plans must compile"
    ex = get_executor(dev_db)
    counts = ex.count_batch(plans)  # warm compile + capacity learning
    # honesty: batch counts must equal per-query device counts on a sample
    # (verify=False when a narrower width already proved agreement on this
    # same store — each probe is a full tunnel RTT)
    if verify:
        for i in (0, width // 2, width - 1):
            if counts[i] is not None:
                expected = compiler.count_matches(
                    dev_db, grounded_query(genes[i])
                )
                assert counts[i] == expected, (
                    f"batch/individual diverged at {i}"
                )
    times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        ex.count_batch(plans)
        times.append(time.perf_counter() - t0)
    answered = sum(c is not None for c in counts)
    return statistics.median(times) / max(answered, 1), width, answered


def served_latency(dev_db, n_clients=16, per_client=6):
    """The serving-edge figure (VERDICT r03 item 5): n_clients concurrent
    threads each issuing sequential single-query RPCs through DasService's
    coalescing path.  Returns (p50_ms per call, wall ms per query).  The
    coalescer batches whatever is in flight into one device program + one
    fetch, so per-query cost under load must land well under one tunnel
    RTT.  Runs with the result cache DISABLED so the series stays
    comparable to the r03-r05 records (repeats would otherwise answer
    from the host-side cache — that regime has its own figures in
    serving_throughput)."""
    import threading

    from das_tpu.api.atomspace import DistributedAtomSpace
    from das_tpu.service.server import DasService

    das = DistributedAtomSpace(database_name="bench_served", db=dev_db)
    service = DasService()
    token = service.attach_tenant("bench_served", das)
    genes = dev_db.get_all_nodes("Gene", names=True)[:n_clients]
    n_clients = len(genes)

    def dsl(g):
        return (
            f"Node n1 Gene {g}, Link Member n1 $3, "
            "Link Member $2 $3, Link Interacts n1 $2, AND"
        )

    def ask(g):
        reply = service.query(
            {"key": token, "query": dsl(g), "output_format": "HANDLE"}
        )
        assert reply["success"], reply["msg"]

    lat = []
    lat_lock = threading.Lock()
    barrier = threading.Barrier(n_clients)

    def client(g):
        barrier.wait()
        for _ in range(per_client):
            t0 = time.perf_counter()
            ask(g)
            dt = time.perf_counter() - t0
            with lat_lock:
                lat.append(dt)

    threads = [threading.Thread(target=client, args=(g,)) for g in genes]
    prev_cache = dev_db.config.result_cache_size
    dev_db.config.result_cache_size = 0
    try:
        ask(genes[0])  # warm the materializing program shape
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
    finally:
        dev_db.config.result_cache_size = prev_cache
    n = n_clients * per_client
    stats = service.coalescer_stats()
    return (
        statistics.median(lat) * 1e3,
        wall / n * 1e3,
        {"clients": n_clients, "per_client": per_client, **stats},
    )


def serving_throughput(dev_db, n_clients=256, per_client=4, rounds=2):
    """Serving-throughput record (ISSUE 2, raised to 256 open-loop
    clients by ISSUE 6): queries/sec under the coalescer with the
    adaptive execution pipeline on (depth floor 2, RTT-adaptive window)
    vs off (depth 1), and the result-cache figures, all on the
    REPEATED-query workload (n_clients client identities over the KB's
    distinct genes — cycled when the KB holds fewer — each issuing
    per_client queries of the hot serving shape).

    The workload is OPEN-LOOP: the whole backlog is submitted to the
    coalescer up front, modeling the north-star regime where the queue is
    never empty (closed-loop synchronous clients can never leave a second
    batch queued, so there is nothing to pipeline).  The drain ceiling is
    capped at half the client count (both arms) so the backlog forms
    multiple batches per drain and the in-flight window can fill.

    The pipelining A/B runs with the result cache DISABLED so both arms
    pay real device work — with the cache on, repeats are host-side dict
    hits and both arms just measure the cache.  The cache then gets its
    own figures: hit rate + qps under repetition, and per-query latency
    of the cache-hit path vs the device path (the >=10x claim in the
    acceptance record).

    `interpret: true` marks a CPU-only run: there is no transport RTT to
    hide, so the qps A/B and time_to_first_row_ms are structural data —
    the perf claims (served_ms_per_query under ~2 ms at 256 clients)
    are meaningful on accelerator runs."""
    from das_tpu import kernels
    from das_tpu.query.fused import get_executor, result_cache_stats

    genes = dev_db.get_all_nodes("Gene", names=True)
    # 256 client identities regardless of KB size: cycle the distinct
    # genes — repeats are the hot serving case (in-batch dedup + cache)
    idents = [genes[i % len(genes)] for i in range(n_clients)]
    # interleaved repeats: [g0..gN, g0..gN, ...] — batches mix distinct
    # queries, repeats land in later batches (in-batch dedup aside)
    workload = [grounded_query(g) for g in idents] * per_client
    mb = max(1, n_clients // 2)

    out = {
        "clients": n_clients,
        "distinct_queries": len(set(idents)),
        "per_client": per_client,
        # true = CPU-only run (no wire to hide): structural data, not a
        # perf claim — same honesty flag as the kernel A/Bs
        "interpret": kernels.interpret_mode(),
    }
    prev_cache = dev_db.config.result_cache_size

    # --- pipelining A/B, cache off (both arms pay device work) -----------
    dev_db.config.result_cache_size = 0
    try:
        serial_qps, _, _, _ = _open_loop_qps(
            dev_db, "bench_pipe_serial", workload, 1, rounds, mb
        )
        piped_qps, piped_stats, piped_ttfr, piped_hist = _open_loop_qps(
            dev_db, "bench_pipe_piped", workload, 2, rounds, mb
        )
    finally:
        dev_db.config.result_cache_size = prev_cache
    out["serial_qps"] = round(serial_qps, 1)
    out["pipelined_qps"] = round(piped_qps, 1)
    out["pipeline_depth"] = 2
    out["pipeline_speedup"] = round(piped_qps / max(serial_qps, 1e-9), 3)
    out["inflight_peak"] = piped_stats["inflight_peak"]
    out["max_batch"] = piped_stats["max_batch"]
    # the open-loop headline (ISSUE 6 target: under ~2 ms at 256 clients
    # on accelerator runs) + the adaptive-window observables
    out["served_ms_per_query"] = round(1e3 / max(piped_qps, 1e-9), 3)
    out["time_to_first_row_ms"] = round(piped_ttfr, 3)
    out["effective_depth"] = piped_stats["effective_depth"]
    out["pipeline_depth_max"] = piped_stats["pipeline_depth_max"]
    out["rtt_ewma_ms"] = piped_stats["rtt_ewma_ms"]
    out["speculative_dispatches"] = piped_stats["speculative_dispatches"]
    out["early_settles"] = piped_stats["early_settles"]
    out["queue_rejections"] = piped_stats["queue_rejections"]
    # histogram-derived open-loop latency distribution (ISSUE 12): the
    # qps figure implies a mean; the tail is what 256 open-loop clients
    # actually feel.  Bucket vector in the full record; p99 in the
    # compact headline (pinned in test_bench_contract).
    pcts = piped_hist.percentiles()
    out["open_loop_p50_ms"] = round(pcts["p50"] or 0.0, 3)
    out["open_loop_p95_ms"] = round(pcts["p95"] or 0.0, 3)
    out["open_loop_p99_ms"] = round(pcts["p99"] or 0.0, 3)
    out["latency_buckets"] = piped_hist.nonzero_buckets()

    # --- result cache: hit rate + qps under repetition -------------------
    before = result_cache_stats(dev_db)
    cached_qps, _, _, _ = _open_loop_qps(
        dev_db, "bench_pipe_cached", workload, 2, rounds, mb
    )
    after = result_cache_stats(dev_db)
    hits = after["hits"] - before["hits"]
    misses = after["misses"] - before["misses"]
    out["cached_qps"] = round(cached_qps, 1)
    out["cache_hit_rate"] = round(hits / max(hits + misses, 1), 3)

    # --- cache-hit path vs device path, same query, per-query ms ---------
    plans = compiler.plan_query(dev_db, grounded_query(genes[0]))
    ex = get_executor(dev_db)
    assert ex.execute(plans, count_only=True, use_cache=True) is not None
    hit_times, dev_times = [], []
    for _ in range(30):
        t0 = time.perf_counter()
        ex.execute(plans, count_only=True, use_cache=True)
        hit_times.append(time.perf_counter() - t0)
    for _ in range(10):
        t0 = time.perf_counter()
        ex.execute(plans, count_only=True)
        dev_times.append(time.perf_counter() - t0)
    hit_ms = statistics.median(hit_times) * 1e3
    dev_ms = statistics.median(dev_times) * 1e3
    out["cache_hit_ms"] = round(hit_ms, 4)
    out["device_path_ms"] = round(dev_ms, 4)
    out["cache_speedup"] = round(dev_ms / max(hit_ms, 1e-9), 1)
    return out


def _open_loop_qps(db, tag, workload, depth, rounds, max_batch):
    """One open-loop serving run (shared by the single-device and mesh
    qps A/Bs so both measure the same methodology): fresh tenant +
    coalescer (fresh stats) over the SAME backing store; best wall time
    of `rounds` backlog drains.  Returns (qps, coalescer snapshot,
    time-to-first-row ms of the best round, per-query latency
    histogram of the best round) — the first-completion callback
    measures how long the FIRST client waited for its rows (the
    streaming-early-settle figure, ISSUE 6), and every client's
    submit→answer wall time lands in a fixed log-bucket histogram
    (das_tpu/obs/metrics.py, ISSUE 12) so the sections report
    p50/p95/p99 open-loop latency without retaining samples — the
    distribution, not just the mean the qps figure implies."""
    from das_tpu.api.atomspace import DistributedAtomSpace, QueryOutputFormat
    from das_tpu.obs.metrics import Histogram
    from das_tpu.service.coalesce import QueryCoalescer
    from das_tpu.service.server import _Tenant

    das = DistributedAtomSpace(
        database_name=tag, db=db, config=DasConfig(pipeline_depth=depth),
    )
    tenant = _Tenant(tag, das)
    coal = QueryCoalescer(max_batch=max_batch, pipeline_depth=depth)
    das.query(workload[0])  # warm the materializing program shape
    best = None
    best_ttfr = None
    best_hist = None
    for _ in range(rounds):
        first = {}
        hist = Histogram("open_loop_ms")

        def mark_first(_fut, _first=first):
            _first.setdefault("t", time.perf_counter())

        t0 = time.perf_counter()
        futs = []
        for q in workload:
            t_submit = time.perf_counter()

            def done(_fut, _t=t_submit, _h=hist):
                _h.observe((time.perf_counter() - _t) * 1e3)

            f = coal.submit(tenant, q, QueryOutputFormat.HANDLE)
            f.add_done_callback(mark_first)
            f.add_done_callback(done)
            futs.append(f)
        for f in futs:
            f.result(timeout=600)
        wall = time.perf_counter() - t0
        ttfr = (first.get("t", t0) - t0) * 1e3
        if best is None or wall < best:
            best, best_ttfr, best_hist = wall, ttfr, hist
    return len(workload) / best, coal.snapshot(), best_ttfr, best_hist


def _chaos_open_loop(db, tag, workload, max_batch, deadline_ms=0,
                     fault_spec=None, breaker_threshold=0):
    """One open-loop serving run that TOLERATES typed failures (the
    chaos twin of _open_loop_qps): every future resolves inside the
    bound as an answer or a typed DasError; anything else is a chaos
    bug and raises.  Returns (qps over ALL submissions, counts dict,
    coalescer snapshot)."""
    from das_tpu import fault
    from das_tpu.api.atomspace import DistributedAtomSpace, QueryOutputFormat
    from das_tpu.core.exceptions import DasDeadlineError, DasError
    from das_tpu.service.coalesce import QueryCoalescer
    from das_tpu.service.server import _Tenant

    das = DistributedAtomSpace(database_name=tag, db=db)
    tenant = _Tenant(tag, das)
    coal = QueryCoalescer(
        max_batch=max_batch, pipeline_depth=2,
        deadline_ms=deadline_ms, breaker_threshold=breaker_threshold,
    )
    das.query(workload[0])  # warm the materializing program shape
    if fault_spec:
        fault.configure(fault_spec)
    try:
        t0 = time.perf_counter()
        futs = [
            coal.submit(tenant, q, QueryOutputFormat.HANDLE)
            for q in workload
        ]
        counts = {"answered": 0, "deadline_misses": 0, "typed_errors": 0}
        for f in futs:
            try:
                f.result(timeout=600)
                counts["answered"] += 1
            except DasDeadlineError:
                counts["deadline_misses"] += 1
            except DasError:
                counts["typed_errors"] += 1
        wall = time.perf_counter() - t0
    finally:
        fault.configure(None)
    return len(workload) / wall, counts, coal.snapshot()


def chaos_serving(dev_db, n_clients=64, per_client=2):
    """Open-loop serving under a FIXED injected fault rate (ISSUE 13):
    the degraded-qps ratio vs the fault-free run, the deadline-miss
    rate under injected latency, and the breaker's trip→probe→restore
    time — the operator's what-does-an-incident-cost record.  Headline
    fields `chaos_qps_ratio` / `breaker_recoveries` are pinned in
    test_bench_contract.  Runs cache-off so injected settle faults
    cannot be absorbed by dict hits; `interpret: true` (CPU) makes the
    ratio structural data, not a perf claim."""
    from das_tpu import fault, kernels

    genes = dev_db.get_all_nodes("Gene", names=True)
    idents = [genes[i % len(genes)] for i in range(n_clients)]
    workload = [grounded_query(g) for g in idents] * per_client
    mb = max(1, n_clients // 2)
    spec = (
        "seed=17;sites=settle_fetch,dispatch_enqueue,cache_insert;"
        "rate=0.05;max=1000000"
    )
    out = {
        "clients": n_clients,
        "per_client": per_client,
        "fault_spec": spec,
        "interpret": kernels.interpret_mode(),
    }
    prev_cache = dev_db.config.result_cache_size
    dev_db.config.result_cache_size = 0
    try:
        clean_qps, _, _ = _chaos_open_loop(
            dev_db, "bench_chaos_clean", workload, mb
        )
        fault.reset_counts()
        chaos_qps, counts, _snap = _chaos_open_loop(
            dev_db, "bench_chaos_faulted", workload, mb, fault_spec=spec
        )
        out["clean_qps"] = round(clean_qps, 1)
        out["chaos_qps"] = round(chaos_qps, 1)
        out["chaos_qps_ratio"] = round(chaos_qps / max(clean_qps, 1e-9), 3)
        out["typed_errors"] = counts["typed_errors"]
        out["answered"] = counts["answered"]
        out["injected"] = {
            s: n for s, n in fault.INJECT_COUNTS.items() if n
        }
        # --- deadline-miss rate under injected dispatch latency ----------
        dl_spec = (
            "seed=23;sites=dispatch_enqueue;mode=latency;latency_ms=25;"
            "rate=0.3;max=1000000"
        )
        _, dl_counts, _ = _chaos_open_loop(
            dev_db, "bench_chaos_deadline", workload, mb,
            deadline_ms=40, fault_spec=dl_spec,
        )
        out["deadline_ms"] = 40
        out["deadline_miss_rate"] = round(
            dl_counts["deadline_misses"] / max(len(workload), 1), 3
        )
        # --- breaker trip -> half-open probe -> restore ------------------
        # one coalescer lives through the whole incident: trip it under
        # injection, stop injecting (the outage ends), and measure how
        # long until a half-open probe restores CLOSED service
        from das_tpu.api.atomspace import (
            DistributedAtomSpace,
            QueryOutputFormat,
        )
        from das_tpu.service.coalesce import QueryCoalescer
        from das_tpu.service.server import _Tenant

        das = DistributedAtomSpace(database_name="bench_chaos_brk",
                                   db=dev_db)
        tenant = _Tenant("bench_chaos_brk", das)
        coal = QueryCoalescer(max_batch=4, pipeline_depth=2,
                              breaker_threshold=1, breaker_cooldown_ms=50)
        fault.configure("seed=29;sites=settle_fetch;every=1;max=1000000")
        try:
            for q in workload[:4]:
                try:
                    coal.submit(
                        tenant, q, QueryOutputFormat.HANDLE
                    ).result(timeout=600)
                except Exception:  # noqa: BLE001 — typed chaos errors
                    pass
        finally:
            fault.configure(None)
        t_open = time.perf_counter()
        recovery_ms = None
        while (time.perf_counter() - t_open) < 30.0:
            try:
                coal.submit(
                    tenant, workload[0], QueryOutputFormat.HANDLE
                ).result(timeout=600)
            except Exception:  # noqa: BLE001 — open-breaker rejections
                pass
            if coal.stats["breaker_state"] == "closed":
                recovery_ms = (time.perf_counter() - t_open) * 1e3
                break
            time.sleep(0.01)
        out["breaker_trips"] = coal.stats["breaker_trips"]
        out["breaker_recoveries"] = coal.stats["breaker_recoveries"]
        out["breaker_recovery_ms"] = (
            None if recovery_ms is None else round(recovery_ms, 1)
        )
    finally:
        dev_db.config.result_cache_size = prev_cache
    return out


def sharded_serving(
    sdata, tensor_db, rounds=2, n_queries=8, n_clients=256, per_client=2
):
    """Sharded serving parity record (ISSUE 3, raised to 256 open-loop
    clients by ISSUE 6): open-loop pipelined-vs-serial qps on the MESH
    path — ShardedDB tenants ride the coalescer's adaptive
    dispatch/settle window (parallel/fused_sharded.py
    dispatch_many/settle_many_iter) — plus a `count_many`
    kernel-vs-lowered A/B on the vmapped count-batch programs
    (query/fused.py count_batch, FusedPlanSig.use_kernels).  Open-loop
    like serving_throughput: 256 client identities cycled over
    n_queries distinct genes, the whole backlog submitted up front so
    the in-flight window can fill; the result cache is disabled for
    BOTH A/Bs so every arm pays real device work.

    `interpret: true` marks a CPU-only run, where BOTH A/Bs are
    structural/correctness data, not perf claims: the kernel arm runs by
    direct discharge, and the qps A/B measures an in-process mesh with
    no transport — pipelining's win comes from hiding the settle RTT
    (~100 ms on a tunneled TPU) behind device execution, so with an
    in-RAM settle the two arms read parity-within-noise.  The structural
    guarantees (pipelined+speculative==serial program counts, the
    in-flight window actually filling, early-settle ordering) are pinned
    in tests/test_zsharded_pipe.py; the perf figure is meaningful on
    accelerator runs."""
    import statistics

    from das_tpu import kernels
    from das_tpu.parallel.sharded_db import ShardedDB

    sdb = ShardedDB(sdata, DasConfig())
    genes = sdb.get_all_nodes("Gene", names=True)[:n_queries]
    idents = [genes[i % len(genes)] for i in range(n_clients)]
    workload = [grounded_query(g) for g in idents] * per_client
    out = {
        "n_shards": int(sdb.tables.n_shards),
        "clients": n_clients,
        "distinct_queries": len(set(idents)),
        "per_client": per_client,
        # true = the kernel arm ran by direct discharge (CPU-only run):
        # the count A/B is then a correctness/telemetry datum, not perf
        "interpret": kernels.interpret_mode(),
    }

    prev_cache = sdb.config.result_cache_size
    sdb.config.result_cache_size = 0  # both arms pay real mesh work
    mb = max(1, n_clients // 2)
    try:
        # interleaved best-of-2 per arm: this box's wall-clock noise
        # (shared cores) dwarfs the depth effect in any single drain, so
        # an A-then-B order would ascribe load spikes to whichever arm
        # drew them; interleaving + best-of keeps the comparison fair
        serial_qps = piped_qps = 0.0
        piped_stats = piped_ttfr = piped_hist = None
        for rep in range(2):
            s, _, _, _ = _open_loop_qps(
                sdb, f"bench_shard_serial{rep}", workload, 1, rounds, mb
            )
            p, stats, ttfr, hist = _open_loop_qps(
                sdb, f"bench_shard_piped{rep}", workload, 2, rounds, mb
            )
            serial_qps = max(serial_qps, s)
            if p >= piped_qps:
                piped_qps, piped_stats, piped_ttfr, piped_hist = (
                    p, stats, ttfr, hist
                )
    finally:
        sdb.config.result_cache_size = prev_cache
    out["serial_qps"] = round(serial_qps, 1)
    out["pipelined_qps"] = round(piped_qps, 1)
    out["pipeline_speedup"] = round(piped_qps / max(serial_qps, 1e-9), 3)
    out["inflight_peak"] = piped_stats["inflight_peak"]
    out["served_ms_per_query"] = round(1e3 / max(piped_qps, 1e-9), 3)
    out["time_to_first_row_ms"] = round(piped_ttfr, 3)
    out["effective_depth"] = piped_stats["effective_depth"]
    out["speculative_dispatches"] = piped_stats["speculative_dispatches"]
    out["early_settles"] = piped_stats["early_settles"]
    out["queue_rejections"] = piped_stats["queue_rejections"]
    # open-loop latency distribution on the mesh path (ISSUE 12) — same
    # histogram layer as the single-device section
    pcts = piped_hist.percentiles()
    out["open_loop_p50_ms"] = round(pcts["p50"] or 0.0, 3)
    out["open_loop_p95_ms"] = round(pcts["p95"] or 0.0, 3)
    out["open_loop_p99_ms"] = round(pcts["p99"] or 0.0, 3)
    out["latency_buckets"] = piped_hist.nonzero_buckets()

    # --- count_many kernel-vs-lowered A/B (vmapped count-batch groups) ---
    from das_tpu.query.fused import get_executor

    ex = get_executor(tensor_db)
    queries = [grounded_query(g) for g in genes]
    prev_mode = tensor_db.config.use_pallas_kernels
    prev_tcache = tensor_db.config.result_cache_size
    env_prev = os.environ.pop("DAS_TPU_PALLAS", None)  # A/B needs both routes
    tensor_db.config.result_cache_size = 0  # time the device, not the cache
    try:
        counts = {}
        for label, mode in (("lowered", "off"), ("kernel", "on")):
            tensor_db.config.use_pallas_kernels = mode
            plans_list = [compiler.plan_query(tensor_db, q) for q in queries]
            before = kernels.DISPATCH_COUNTS["count_kernel"]
            ex.count_batch(plans_list)  # warm compile + caps
            times = []
            for _ in range(rounds + 1):
                t0 = time.perf_counter()
                counts[label] = ex.count_batch(plans_list)
                times.append(time.perf_counter() - t0)
            out[f"count_{label}_ms"] = round(statistics.median(times) * 1e3, 3)
            if label == "kernel":
                # honesty flag: did the group program actually route
                # through the kernels, or did the size guard decline?
                out["count_kernel_engaged"] = (
                    kernels.DISPATCH_COUNTS["count_kernel"] > before
                )
        out["count_parity"] = counts["kernel"] == counts["lowered"]
    finally:
        tensor_db.config.use_pallas_kernels = prev_mode
        tensor_db.config.result_cache_size = prev_tcache
        if env_prev is not None:
            os.environ["DAS_TPU_PALLAS"] = env_prev
    return out


def kernel_ab(dev_db, rounds=5):
    """Kernel-vs-lowered A/B on the headline 3-var count query: same
    store, same query, both routes — the executor caches kernel and
    lowered executables side by side (FusedPlanSig.use_kernels), so each
    side times its own compiled program.  Off-TPU the kernels run in
    interpret mode (flagged `interpret: true`): the record is then a
    correctness/telemetry datum, not a perf claim — the perf target is
    the TPU Mosaic compile."""
    from das_tpu import kernels

    q = three_var_query()
    out = {"interpret": kernels.interpret_mode()}
    prev = dev_db.config.use_pallas_kernels
    # DAS_TPU_PALLAS beats the config in kernels.enabled(); it must not
    # beat the A/B, which needs BOTH routes — lift it for the measurement
    env_prev = os.environ.pop("DAS_TPU_PALLAS", None)
    try:
        for label, mode in (("lowered", "off"), ("kernel", "on")):
            dev_db.config.use_pallas_kernels = mode
            compiler.count_matches(dev_db, q)  # warm compile + caps
            before = (
                kernels.DISPATCH_COUNTS["fused_kernel"]
                + kernels.DISPATCH_COUNTS["kernel"]
            )
            times = []
            for _ in range(rounds):
                t0 = time.perf_counter()
                compiler.count_matches(dev_db, q)
                times.append(time.perf_counter() - t0)
            out[f"{label}_ms"] = round(statistics.median(times) * 1e3, 3)
            if label == "kernel":
                # honesty flag: did a kernel actually dispatch (fused
                # kernel program OR staged-path kernel calls), or did the
                # size guard fall back to the lowered ops throughout?
                out["kernel_engaged"] = (
                    kernels.DISPATCH_COUNTS["fused_kernel"]
                    + kernels.DISPATCH_COUNTS["kernel"]
                ) > before
        from das_tpu.core.config import DasConfig as _Cfg

        out["route"] = kernels.route_label(_Cfg(use_pallas_kernels="on"))
    finally:
        dev_db.config.use_pallas_kernels = prev
        if env_prev is not None:
            os.environ["DAS_TPU_PALLAS"] = env_prev
    return out


def tiled_kernel_ab(rounds=3):
    """Grid-chunked kernel A/B at FlyBase-shape scale (ISSUE 4): a
    SYNTHETIC >2^18-row term — a posting table past the old
    single-block row bound (KERNEL_MAX_ROWS, 2^18) whose probe window
    and join output the bytes planner (kernels/budget.py) grid-chunks —
    timed kernel-route vs the lowered op chains on identical inputs.

    The table is synthetic numpy (no KB build: the point is the kernel
    shapes, not ingest).  `tiled_route` records the planner verdicts;
    the A/B asserts NO SILENT FALLBACK — after the kernel arms,
    DISPATCH_COUNTS must show zero lowered launches and a kernel_tiled
    launch, else the run aborts into the error field rather than
    reporting a kernel time that secretly measured the lowered ops.
    Off-TPU (`interpret: true`) both arms are correctness/telemetry
    data, not perf claims — the perf target is the TPU Mosaic compile."""
    import statistics

    import jax.numpy as jnp
    import numpy as np

    from das_tpu import kernels
    from das_tpu.kernels import budget as kbudget
    from das_tpu.ops import posting
    from das_tpu.ops.join import _build_term_table_impl, _join_tables_impl

    rng = np.random.default_rng(2024)
    n = 1 << 19                      # 524288 rows: 2x the old bound
    probe_cap = 1 << 19
    # one fat key owns >2^18 rows — the whole-table-term probe shape
    fat = np.zeros(n, np.int64)
    fat[(1 << 18) + (1 << 16):] = np.arange(n - (1 << 18) - (1 << 16)) + 1
    keys = jnp.asarray(np.sort(fat))
    perm = jnp.asarray(rng.permutation(n).astype(np.int32))
    targets = jnp.asarray(rng.integers(0, 1 << 20, (n, 2)).astype(np.int32))
    key = np.int64(0)

    L = R = 2048
    join_cap = 1 << 19
    lv = jnp.asarray(rng.integers(0, 8, (L, 2)).astype(np.int32))
    rv = jnp.asarray(rng.integers(0, 8, (R, 2)).astype(np.int32))
    lm = jnp.asarray(np.ones(L, bool))
    rm = jnp.asarray(np.ones(R, bool))
    jargs = (lv, lm, rv, rm, ((0, 0),), (1,), join_cap)

    probe_plan = kbudget.probe_plan(n, n, 2, 2, probe_cap)
    join_plan = kbudget.join_plan(L, 2, R, 2, 1, 3, join_cap)
    out = {
        "interpret": kernels.interpret_mode(),
        "rows": n,
        "probe_cap": probe_cap,
        "join_cap": join_cap,
        "route": probe_plan.route,
        "tiled_route": {
            "probe": probe_plan.route, "join": join_plan.route,
            "chunk_rows": probe_plan.chunk_rows,
        },
    }

    @jax.jit
    def lowered_probe(keys, perm, targets, key):
        local, valid, cnt = posting.range_probe(keys, perm, key, probe_cap)
        vals, mask = _build_term_table_impl(targets, local, valid, (0, 1), ())
        return vals, mask, cnt

    lowered_join = jax.jit(
        lambda *a: _join_tables_impl(*a, ((0, 0),), (1,), join_cap)
    )

    def timed(fn, *a):
        best = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            r = fn(*a)
            jax.block_until_ready(r)
            best.append(time.perf_counter() - t0)
        return r, statistics.median(best) * 1e3

    pw, out["probe_lowered_ms"] = timed(lowered_probe, keys, perm, targets, key)
    jw, out["join_lowered_ms"] = timed(lowered_join, lv, lm, rv, rm)

    env_prev = os.environ.pop("DAS_TPU_PALLAS", None)
    try:
        kernels.reset_dispatch_counts()
        pk, out["probe_kernel_ms"] = timed(
            lambda: kernels.probe_term_table(
                keys, perm, targets, key, np.zeros(0, np.int32), probe_cap,
                var_cols=(0, 1), eq_pairs=(), extra_fixed=(),
            )
        )
        jk, out["join_kernel_ms"] = timed(lambda: kernels.join_tables(*jargs))
        c = kernels.DISPATCH_COUNTS
        # no-silent-fallback: both eligible shapes must have launched
        # kernels (at least one grid-chunked) and ZERO lowered ops
        out["no_lowered_fallback"] = (
            c["lowered"] == 0 and c["kernel"] >= 2 and c["kernel_tiled"] >= 1
        )
        assert out["no_lowered_fallback"], f"silent lowered fallback: {c}"
    finally:
        if env_prev is not None:
            os.environ["DAS_TPU_PALLAS"] = env_prev
    out["parity"] = bool(
        all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(pk, pw)
        )
        and all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jk, jw)
        )
    )
    out["tiled_vs_lowered_ms"] = [
        round(out["probe_kernel_ms"] + out["join_kernel_ms"], 3),
        round(out["probe_lowered_ms"] + out["join_lowered_ms"], 3),
    ]
    for k in (
        "probe_kernel_ms", "probe_lowered_ms",
        "join_kernel_ms", "join_lowered_ms",
    ):
        out[k] = round(out[k], 3)
    return out


def planner_ab(rounds=3):
    """Cost-based planner A/B (ISSUE 8): planner-vs-greedy on SKEW-HEAVY
    FlyBase-shape terms — hub processes whose degrees sit far above the
    median, the regime where greedy's blind capacity seeds materialize
    most and every under-seeded join pays a capacity-retry tier (a fresh
    XLA compile per tier).

    Workload: fan-out joins grounded on the hub processes
    (Member(G, p_hub) ⋈ Member(G, P2)) plus the analytic 3-var query.
    Each arm gets a FRESH TensorDB (fresh executor caches) and the
    CapStore is disabled so neither arm inherits the other's learned
    capacities.  Reported: first-contact wall time (compiles included —
    that IS the planner's win), warm per-query ms (best-of-rounds),
    compiled fused program counts, retry_rounds_avoided =
    greedy_programs - planner_programs, and answer parity."""
    from das_tpu import kernels
    from das_tpu import planner as planner_mod
    from das_tpu.api.atomspace import DistributedAtomSpace
    from das_tpu.query import fused as fused_mod

    data, _, _ = build_bio_atomspace(
        n_genes=2000, n_processes=60, members_per_gene=8,
        n_interactions=4000, seed=17, skew=1.1,
    )
    probe_db = TensorDB(data, DasConfig())
    # the skew-heavy terms: the most-populated (hub) processes
    procs = probe_db.get_all_nodes("BiologicalProcess", names=True)
    ex = fused_mod.get_executor(probe_db)
    by_deg = sorted(
        procs,
        key=lambda p: ex._estimate(compiler.plan_query(
            probe_db, Link("Member", [Variable("G"),
                                      Node("BiologicalProcess", p)], True)
        )[0]),
        reverse=True,
    )
    hubs = by_deg[:6]
    del probe_db, ex
    queries = [
        And([
            Link("Member", [Variable("G"),
                            Node("BiologicalProcess", p)], True),
            Link("Member", [Variable("G"), Variable("P2")], True),
        ])
        for p in hubs
    ] + [three_var_query()]

    out = {"clauses": len(queries), "skew": 1.1}
    answers = {}
    env_prev = os.environ.pop("DAS_TPU_XLA_CACHE", None)
    os.environ["DAS_TPU_XLA_CACHE"] = "0"
    # DAS_TPU_PLANNER beats the config in planner.enabled(); an exported
    # value must not collapse both arms onto one path (the kernel A/B
    # lifts DAS_TPU_PALLAS for the same reason)
    planner_env_prev = os.environ.pop("DAS_TPU_PLANNER", None)
    try:
        for label, mode in (("planner", "on"), ("greedy", "off")):
            db = TensorDB(data, DasConfig(use_planner=mode))
            das = DistributedAtomSpace(database_name=f"pab_{label}", db=db)
            kernels.reset_dispatch_counts()
            planner_mod.reset_planner_counts()
            t0 = time.perf_counter()
            # parity compares ASSIGNMENT SETS, not formatted strings —
            # str(set) is insertion-order-sensitive, and a planner-chosen
            # join order legitimately changes row (hence insertion) order
            # while binding exactly the same answers
            answers[label] = [
                frozenset(das.query_answer(q)[1].assignments)
                for q in queries
            ]
            out[f"{label}_first_contact_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 3
            )
            out[f"{label}_programs"] = kernels.DISPATCH_COUNTS["fused"]
            best = float("inf")
            for _ in range(rounds):
                t0 = time.perf_counter()
                for q in queries:
                    das.query(q)
                best = min(best, time.perf_counter() - t0)
            out[f"{label}_ms"] = round(best * 1e3 / len(queries), 3)
            if label == "planner":
                out["planner_stats"] = planner_mod.snapshot()
                out["planner_route"] = planner_mod.explain(
                    db, queries[0]
                )["route"]
            del das, db
    finally:
        del os.environ["DAS_TPU_XLA_CACHE"]
        if env_prev is not None:
            os.environ["DAS_TPU_XLA_CACHE"] = env_prev
        if planner_env_prev is not None:
            os.environ["DAS_TPU_PLANNER"] = planner_env_prev
    out["retry_rounds_avoided"] = (
        out["greedy_programs"] - out["planner_programs"]
    )
    out["parity"] = answers["planner"] == answers["greedy"]
    assert out["parity"], "planner answers diverged from greedy"
    return out


def multiway_ab(rounds=3):
    """Worst-case-optimal multiway join A/B (ISSUE 9): planner-routed
    k-way intersection vs the binary-join chain on the SKEW-HEAVY hub
    fan-out star (three Member clauses sharing the process variable at
    skew 1.1 — the chain's second intermediate rides the independence
    model, which errs low exactly on skew, so its capacity seed pays a
    retry tier; the multiway route's ONE output buffer seeds from the
    exact k-way degree product) plus the 3-var analytic triangle (a
    2-clause star prefix + binary tail — parity coverage for the mixed
    program).

    Each arm gets a FRESH TensorDB (fresh executor caches), the CapStore
    is disabled, DAS_TPU_STAR=0 keeps the star count on the executors
    whose capacities are the thing under test, and DAS_TPU_MULTIWAY is
    lifted so the config decides the arm.  In-bench assertions: star
    counts AND analytic assignment sets identical across arms
    (bit-parity), and the multiway arm must actually dispatch a
    fused_multiway program (no silent chain fallback).  Reported:
    first-contact wall time, warm per-query ms, compiled fused program
    counts, chain_retry_rounds_avoided = chain_programs -
    multiway_programs, and the planner's route/est-vs-actual."""
    from das_tpu import kernels
    from das_tpu import planner as planner_mod
    from das_tpu.api.atomspace import DistributedAtomSpace

    data, _, _ = build_bio_atomspace(
        n_genes=120, n_processes=40, members_per_gene=3,
        n_interactions=300, seed=17, skew=1.1,
    )
    star = And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Member", [Variable("V4"), Variable("V3")], True),
    ])
    analytic = three_var_query()

    out = {"skew": 1.1, "interpret": kernels.interpret_mode()}
    counts = {}
    answers = {}
    saved_env = {}
    for name in ("DAS_TPU_XLA_CACHE", "DAS_TPU_MULTIWAY", "DAS_TPU_STAR"):
        saved_env[name] = os.environ.pop(name, None)
    os.environ["DAS_TPU_XLA_CACHE"] = "0"
    os.environ["DAS_TPU_STAR"] = "0"
    try:
        for label, mode in (("multiway", "auto"), ("chain", "off")):
            db = TensorDB(data, DasConfig(use_multiway=mode))
            das = DistributedAtomSpace(database_name=f"mab_{label}", db=db)
            kernels.reset_dispatch_counts()
            planner_mod.reset_planner_counts()
            t0 = time.perf_counter()
            counts[label] = compiler.count_matches(db, star)
            answers[label] = frozenset(
                das.query_answer(analytic)[1].assignments
            )
            out[f"{label}_first_contact_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 3
            )
            out[f"{label}_programs"] = kernels.DISPATCH_COUNTS["fused"]
            if label == "multiway":
                # no-silent-fallback: the k-way route must have RUN
                assert kernels.DISPATCH_COUNTS["fused_multiway"] >= 1, (
                    f"multiway arm never dispatched: "
                    f"{kernels.DISPATCH_COUNTS}"
                )
                out["multiway_stats"] = planner_mod.snapshot()
                out["multiway_route"] = planner_mod.explain(db, star)[
                    "route"
                ]
            best = float("inf")
            for _ in range(rounds):
                t0 = time.perf_counter()
                compiler.count_matches(db, star)
                das.query(analytic)
                best = min(best, time.perf_counter() - t0)
            out[f"{label}_ms"] = round(best * 1e3 / 2, 3)
            del das, db
    finally:
        del os.environ["DAS_TPU_XLA_CACHE"]
        del os.environ["DAS_TPU_STAR"]
        for name, prev in saved_env.items():
            if prev is not None:
                os.environ[name] = prev
    out["chain_retry_rounds_avoided"] = (
        out["chain_programs"] - out["multiway_programs"]
    )
    out["parity"] = (
        counts["multiway"] == counts["chain"]
        and answers["multiway"] == answers["chain"]
    )
    assert out["parity"], "multiway answers diverged from the chain"
    return out


def tree_fused_ab(rounds=3):
    """Whole-tree fused execution A/B (ISSUE 10): one planner-costed
    program for an N-branch Or vs the tree executor's per-site
    composites.  Workload: 3-branch grounded-Member Or unions plus a
    de-Morgan negation variant on the bio KB — the serving-shaped
    disjunction family, where the tree executor pays one
    dispatch/settle round trip per branch (the ~RTT-per-trip wire cost
    the ROADMAP serving item hides) and the fused route settles
    everything in ONE transfer.

    Each arm gets a FRESH TensorDB (fresh executor caches), the
    CapStore is disabled, DAS_TPU_TREE_FUSION is lifted so the config
    decides the arm, and the result caches are OFF (result_cache_size=0)
    so the warm rounds time the device path — the per-branch
    dispatch/settle cost IS the thing under test, and both arms would
    otherwise settle into cache hits.  In-bench assertions: assignment
    sets identical across arms (bit-parity) and the fused arm must
    actually dispatch a fused_tree program (no silent fallback).
    Reported: first-contact wall time, warm per-query ms, device
    program counts, tree_programs_avoided = tree_programs -
    fused_programs, and the planner's whole-tree route."""
    from das_tpu import kernels
    from das_tpu import planner as planner_mod
    from das_tpu.api.atomspace import DistributedAtomSpace

    data, _, _ = build_bio_atomspace(
        n_genes=120, n_processes=30, members_per_gene=4,
        n_interactions=200, seed=17,
    )
    probe_db = TensorDB(data, DasConfig())
    genes = probe_db.get_all_nodes("Gene", names=True)[:4]
    del probe_db

    def branch(g):
        return And([
            Link("Member", [Node("Gene", g), Variable("V3")], True),
            Link("Member", [Variable("V2"), Variable("V3")], True),
        ])

    queries = [
        Or([branch(g) for g in genes[:3]]),
        Or([branch(genes[1]), branch(genes[3])]),
        Or([branch(genes[0]), Not(branch(genes[2]))]),
    ]

    out = {
        # per-query Or branch counts (negative branches included):
        # tree_programs_avoided arithmetic reads off these
        "branches": [len(q.terms) for q in queries],
        "queries": len(queries),
        "interpret": kernels.interpret_mode(),
    }
    answers = {}
    saved_env = {}
    for name in ("DAS_TPU_XLA_CACHE", "DAS_TPU_TREE_FUSION"):
        saved_env[name] = os.environ.pop(name, None)
    os.environ["DAS_TPU_XLA_CACHE"] = "0"
    try:
        for label, mode in (("fused", "on"), ("tree", "off")):
            db = TensorDB(data, DasConfig(
                use_tree_fusion=mode, result_cache_size=0,
            ))
            das = DistributedAtomSpace(database_name=f"tfab_{label}", db=db)
            kernels.reset_dispatch_counts()
            t0 = time.perf_counter()
            answers[label] = [
                frozenset(das.query_answer(q)[1].assignments)
                for q in queries
            ]
            out[f"{label}_first_contact_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 3
            )
            out[f"{label}_programs"] = (
                kernels.DISPATCH_COUNTS["fused_tree"]
                + kernels.DISPATCH_COUNTS["fused"]
            )
            if label == "fused":
                # no-silent-fallback: the whole-tree route must have RUN
                assert kernels.DISPATCH_COUNTS["fused_tree"] >= 1, (
                    f"fused-tree arm never dispatched: "
                    f"{kernels.DISPATCH_COUNTS}"
                )
                out["tree_fused_route"] = planner_mod.explain(
                    db, queries[0]
                )["route"]
            best = float("inf")
            for _ in range(rounds):
                t0 = time.perf_counter()
                for q in queries:
                    das.query(q)
                best = min(best, time.perf_counter() - t0)
            out[f"{label}_ms"] = round(best * 1e3 / len(queries), 3)
            del das, db
    finally:
        del os.environ["DAS_TPU_XLA_CACHE"]
        for name, prev in saved_env.items():
            if prev is not None:
                os.environ[name] = prev
    out["tree_programs_avoided"] = (
        out["tree_programs"] - out["fused_programs"]
    )
    out["parity"] = answers["fused"] == answers["tree"]
    assert out["parity"], "fused-tree answers diverged from the tree executor"
    return out


def staged_dispatch_counts(db):
    """Dispatched-ops count for ONE staged 3-var query, kernel vs lowered
    route (the dispatch-count regression test pins the same numbers:
    tests/test_zkernels.py)."""
    from das_tpu import kernels

    plans = compiler.plan_query(db, three_var_query())
    out = {}
    prev = db.config.use_pallas_kernels
    env_prev = os.environ.pop("DAS_TPU_PALLAS", None)  # same lift as kernel_ab
    try:
        for label, mode in (("lowered", "off"), ("kernel", "on")):
            db.config.use_pallas_kernels = mode
            kernels.reset_dispatch_counts()
            compiler.execute_plan(db, plans)
            c = kernels.DISPATCH_COUNTS
            out[label] = c["kernel"] + c["lowered"]
    finally:
        db.config.use_pallas_kernels = prev
        if env_prev is not None:
            os.environ["DAS_TPU_PALLAS"] = env_prev
    return out


def durability_section(dev_db, n_commits=3):
    """dasdur record (ISSUE 15): `restore_s` — verified snapshot + WAL
    replay + warm bundle vs a full rebuild from bare records (finalize
    + upload, the pre-dasdur replica cold start) on the SAME store;
    `wal_replay_commits_per_s` — replay throughput of the write-ahead
    delta log, measured on the replay loop alone; and the
    chaos-recovery wall time — a crash injected mid-snapshot, then
    restore() back to a bit-parity store (asserted in-bench).  Compact
    headline field `restore_s` is pinned in test_bench_contract.
    `interpret: true` (CPU) marks the figures structural data, not a
    device perf claim — the device-scale win is FlyBase's 178 s build
    + 76 s finalize avoided.  n_commits models a replica inheriting a
    RECENT snapshot (replay cost is linear in WAL length — the
    per-commit rate is the separate wal_replay_commits_per_s figure;
    an operator bounds it by snapshotting periodically)."""
    import shutil
    import tempfile

    from das_tpu import fault, kernels
    from das_tpu.api.atomspace import DistributedAtomSpace
    from das_tpu.core.config import DasConfig
    from das_tpu.core.exceptions import InjectedFault
    from das_tpu.storage import checkpoint, durable
    from das_tpu.storage.tensor_db import TensorDB

    root = tempfile.mkdtemp(prefix="das_bench_dur_")
    out = {"interpret": kernels.interpret_mode(), "commits": n_commits}
    das = DistributedAtomSpace(database_name="bench_dur", db=dev_db)
    genes = dev_db.get_all_nodes("Gene", names=True)[:4]
    queries = [grounded_query(g) for g in genes]
    baseline = [das.query(q) for q in queries]
    try:
        # -- snapshot, then WAL-logged commits ---------------------------
        t0 = time.perf_counter()
        durable.write_snapshot(dev_db, root)
        out["snapshot_s"] = round(time.perf_counter() - t0, 3)
        g0 = genes[0]
        for i in range(n_commits):
            tx = das.open_transaction()
            tx.add(f'(: "BENCHDUR:{i}" Gene)')
            tx.add(f'(: "{g0}" Gene)')
            tx.add(f'(Interacts "BENCHDUR:{i}" "{g0}")')
            das.commit_transaction(tx)
        live = [das.query(q) for q in queries]

        # -- rebuild arm: bare records -> finalize -> upload -------------
        # (best-of-2 per arm: the shared records parse dominates both
        # arms on CPU and its variance would otherwise swamp the
        # finalize-vs-replay difference under measurement)
        gen_dir = durable.list_generations(root)[-1][1]

        def rebuild_arm():
            data = checkpoint.load(gen_dir, _verified=True)
            data._fin = None  # bare-records cold start pays the finalize
            TensorDB(data, DasConfig())

        out["rebuild_s"] = round(_best_of(rebuild_arm, rounds=2), 3)

        # -- restore arm: verified snapshot + WAL replay + warm bundle ---
        replayed_before = durable.DUR_STATS["recovery_replayed"]
        arm = {}

        def restore_arm():
            arm["db"] = TensorDB.restore(root)

        out["restore_s"] = round(_best_of(restore_arm, rounds=2), 3)
        restored = arm["db"]
        out["wal_records_replayed"] = (
            durable.DUR_STATS["recovery_replayed"] - replayed_before
        ) // 2
        out["restore_vs_rebuild"] = round(
            out["rebuild_s"] / max(out["restore_s"], 1e-9), 2
        )
        das_r = DistributedAtomSpace(database_name="bench_dur_r",
                                     db=restored)
        answers = [das_r.query(q) for q in queries]
        assert answers == live, "restored answers diverged from live"

        # -- WAL replay throughput (the replay loop alone) ---------------
        data2, manifest, gen_dir2 = durable.newest_valid_generation(root)
        db2 = TensorDB(data2, DasConfig())
        db2.delta_version = int(manifest["delta_version"])
        t0 = time.perf_counter()
        replayed = durable.replay_wal(db2, gen_dir2, manifest)
        replay_s = time.perf_counter() - t0
        out["wal_replay_commits_per_s"] = round(
            replayed / max(replay_s, 1e-9), 1
        )
        del db2, data2

        # -- chaos recovery: crash mid-snapshot, recover to parity -------
        fault.configure("seed=31;sites=snapshot_write;every=1;max=1")
        try:
            durable.write_snapshot(restored, root)
            out["chaos_crash_typed"] = False  # injection missed: a bug
        except InjectedFault:
            out["chaos_crash_typed"] = True
        finally:
            fault.configure(None)
        # recovery wall starts AFTER the crash: the doomed snapshot's
        # serialization work is the incident, not the recovery
        t0 = time.perf_counter()
        recovered = TensorDB.restore(root)
        out["chaos_recovery_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 1
        )
        das_c = DistributedAtomSpace(database_name="bench_dur_c",
                                     db=recovered)
        assert [das_c.query(q) for q in queries] == live, (
            "chaos-recovered answers diverged"
        )
        assert baseline is not None  # pre-commit answers kept for context
        del recovered, restored
    finally:
        fault.configure(None)
        # detach: the WAL lives inside the temp root being deleted — a
        # later commit on dev_db must not append into a removed dir
        dev_db._wal = None
        dev_db._snapshot_root = None
        shutil.rmtree(root, ignore_errors=True)
    return out


def _device_bytes(dev_db) -> int:
    total = 0
    for bucket in dev_db.dev.buckets.values():
        for name in vars(bucket):
            v = getattr(bucket, name)
            if hasattr(v, "nbytes"):
                total += v.nbytes
            elif isinstance(v, list):
                total += sum(x.nbytes for x in v if hasattr(x, "nbytes"))
    for name in ("node_type_id", "incoming_offsets", "incoming_links"):
        total += getattr(dev_db.dev, name).nbytes
    return total


def flybase_scale_section():
    """Scale proof at the reference baseline KB size: build + finalize +
    upload a ~2.58M-node / ~27.9M-link atomspace, measure grounded-query
    latency (sequential and at batch width) and pattern-miner throughput
    (ms per halo link, vs the reference's 74-104 ms/link loop,
    SimplePatternMiner.ipynb cell 9)."""
    _enable_proflog()
    from das_tpu.mining.miner import PatternMiner

    def log(msg):
        print(f"[flybase] {msg}", file=sys.stderr, flush=True)

    fb_scale = float(os.environ.get("DAS_BENCH_FLYBASE_SCALE", "1"))
    cfg = {
        k: (v if k == "members_per_gene" else max(1, int(v * fb_scale)))
        for k, v in FLYBASE.items()
    }
    # --- end-to-end FILE ingest at reference scale (VERDICT r02 item 4):
    # the KB arrives through the real parse->encode path (canonical .metta
    # via the C++ scanner when built), not an in-process builder.  The
    # write phase is input GENERATION, reported separately.
    import resource
    import tempfile

    from das_tpu.ingest.pipeline import load_canonical_knowledge_base
    from das_tpu.models.bio import write_bio_canonical
    from das_tpu.storage.atom_table import AtomSpaceData

    ingest_dir = tempfile.mkdtemp(prefix="das_bench_ingest_")
    metta_path = os.path.join(ingest_dir, "bio_canonical.metta")
    from das_tpu.ingest import native as native_mod

    try:
        t0 = time.perf_counter()
        write_bio_canonical(metta_path, **cfg)
        generate_s = time.perf_counter() - t0
        size_mb = os.path.getsize(metta_path) / 1e6
        log(f"generated {size_mb:.0f} MB canonical .metta in {generate_s:.0f}s")
        t0 = time.perf_counter()
        data = AtomSpaceData()
        load_canonical_knowledge_base(data, metta_path)
        ingest_s = time.perf_counter() - t0
    finally:
        # a parse error / OOM must not leak the multi-GB temp file
        import shutil

        shutil.rmtree(ingest_dir, ignore_errors=True)
    maxrss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # ru_maxrss is KiB on Linux, bytes on macOS
    peak_rss_gb = maxrss * (1 if sys.platform == "darwin" else 1024) / 1e9
    nodes, links = data.count_atoms()
    log(
        f"ingested {nodes} nodes / {links} links in {ingest_s:.0f}s "
        f"({size_mb / max(ingest_s, 1e-9):.0f} MB/s, "
        f"peak RSS {peak_rss_gb:.1f} GB)"
    )
    t0 = time.perf_counter()
    # whole-table probes legitimately reach ~24M rows at this scale
    db = TensorDB(data, DasConfig(max_result_capacity=1 << 26))
    finalize_upload_s = time.perf_counter() - t0
    log(f"finalize+upload {finalize_upload_s:.0f}s")

    out = {
        "kb_nodes": nodes,
        "kb_links": links,
        "ingest_generate_s": round(generate_s, 1),
        "ingest_file_mb": round(size_mb, 1),
        "ingest_s": round(ingest_s, 1),
        "ingest_mb_per_s": round(size_mb / max(ingest_s, 1e-9), 1),
        "ingest_expressions_per_s": round(links / max(ingest_s, 1e-9)),
        "ingest_native_scanner": native_mod.native_available(),
        "ingest_peak_rss_gb": round(peak_rss_gb, 1),
        # build_s keeps the r01/r02 series meaning "time to a populated
        # host store" — now generation + file ingest instead of the
        # in-process builder
        "build_s": round(generate_s + ingest_s, 1),
        "finalize_upload_s": round(finalize_upload_s, 1),
        "device_index_mb": round(_device_bytes(db) / 1e6),
        "reference_miner_ms_per_link": "74-104",
    }
    # stream the build stats immediately: if a later measurement hangs and
    # the parent kills this child, the scale proof (store built, uploaded,
    # footprint) survives as the last parseable line
    print(json.dumps(out), flush=True)

    # every measurement is independent: a transient failure (e.g. a
    # dropped remote-compile over the TPU tunnel) costs one entry, not
    # the whole scale proof
    def measure(name, fn):
        try:
            fn()
        except Exception as e:
            log(f"{name} failed: {e!r}")
            out[f"{name}_error"] = repr(e)

    def _batched_fresh():
        # same measurement as _batched but BEFORE the commit/miner stages
        # mutate the store (delta overlay, host-fold caches, index
        # threads): the r04 0.944 -> 1.284 ms/query spread could not be
        # attributed because only the post-everything number existed
        # (VERDICT r04 item 2).  fresh vs final now brackets the cost of
        # measurement-order state within ONE run.
        batch_s, bw, _ = batched_per_query(db, rounds=3)
        log(f"batched(fresh) {batch_s * 1e3:.2f} ms/query at width {bw}")
        out["batched_fresh_ms_per_query"] = round(batch_s * 1e3, 3)

    def _batched():
        # quiesce first: join any in-flight digest-index build and drop
        # collected garbage so the number is steady-state, not whatever
        # background work the previous stage left running on this 1-core
        # host
        core = db.data.columnar
        if core is not None:
            core.wait_indexes()
        import gc

        gc.collect()
        batch_s, bw, answered = batched_per_query(db, rounds=3)
        log(f"batched {batch_s * 1e3:.2f} ms/query at width {bw}")
        out["batched_ms_per_query"] = round(batch_s * 1e3, 3)
        out["batch_width"] = bw
        out["batch_answered"] = answered

    def _sequential():
        genes = db.get_all_nodes("Gene", names=True)[:4]
        compiler.count_matches(db, grounded_query(genes[0]))
        times = []
        for g in genes:
            t0 = time.perf_counter()
            compiler.count_matches(db, grounded_query(g))
            times.append(time.perf_counter() - t0)
        seq_p50 = statistics.median(times)
        rtt = transport_rtt_ms()
        fetches = fetches_per_query(db, grounded_query(genes[0]))
        log(f"sequential p50 {seq_p50 * 1e3:.1f} ms "
            f"(rtt {rtt:.1f} ms x {fetches} fetches)")
        out["sequential_p50_ms"] = round(seq_p50 * 1e3, 2)
        out["transport_rtt_ms"] = round(rtt, 2)
        out["fetches_per_query"] = fetches

    def _device_only():
        genes = db.get_all_nodes("Gene", names=True)
        plans = {}

        def plans_for(w):
            if w not in plans:
                plans[w] = [
                    compiler.plan_query(db, grounded_query(g))
                    for g in genes[:w]
                ]
            return plans[w]

        ms, method = device_only_ms(db, plans_for, w1=16, w2=128, rounds=3)
        log(f"device-only {ms:.3f} ms/query (grounded, method={method})")
        out["sequential_device_only_ms"] = round(ms, 3)
        out["sequential_device_only_method"] = method

    def _commit():
        # incremental commit: 10 new expressions on the multi-million-link
        # store must not re-finalize/re-upload (delta path, VERDICT r1 #4).
        # Two measurements: the FIRST commit pays one-time fixed-shape
        # program compiles (capacity-padded buckets keep shapes stable);
        # the second is the steady-state cost — pure O(delta+n) device work
        from das_tpu.storage.atom_table import load_metta_text

        def one_commit(tag):
            commit_text = "\n".join(
                [f'(: "NG{tag}_{i}" Gene)' for i in range(5)]
                + [
                    f'(Interacts "NG{tag}_{i}" "NG{tag}_{(i + 1) % 5}")'
                    for i in range(5)
                ]
            )
            t0 = time.perf_counter()
            load_metta_text(commit_text, db.data)
            db.refresh()
            return time.perf_counter() - t0

        cold = one_commit(0)
        warm = one_commit(1)
        # steady state: the cold commit kicks the digest-index build off
        # on a background thread; on a 1-core host it contends with the
        # next commit's linear probes, so the honest series is
        # cold / warm-while-building / steady-after-build
        core = db.data.columnar
        if core is not None and core._index_thread is not None:
            core._index_thread.join(timeout=60)
        steady = one_commit(2)
        log(
            f"10-expression commit cold {cold:.3f}s warm {warm:.3f}s "
            f"steady {steady:.3f}s"
        )
        out["commit_10_expressions_s"] = round(cold, 3)
        out["commit_10_expressions_warm_s"] = round(warm, 3)
        out["commit_10_expressions_steady_s"] = round(steady, 4)

    def _miner():
        miner = PatternMiner(db, halo_length=2, link_rate=0.01, seed=7)
        genes = db.get_all_nodes("Gene", names=True)[:3]
        gene_handles = [db.get_node_handle("Gene", g) for g in genes]
        t0 = time.perf_counter()
        universe = miner.expand_halo(gene_handles)
        halo_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        n_candidates = miner.build_patterns()
        count_s = time.perf_counter() - t0
        # route telemetry for the joint phase: how many counts the batch
        # answered vs fell to per-query dispatches (each a tunnel RTT) —
        # the steering signal for further joint-phase work
        from das_tpu.query import fused as fused_mod
        from das_tpu.query import starcount as star_mod

        compiler.reset_route_counts()
        fetches_before = fused_mod.FETCH_COUNTS["n"] + star_mod.FETCHES["n"]
        t0 = time.perf_counter()
        best = miner.mine(ngram=3, epochs=100)
        mine_s = time.perf_counter() - t0
        out["miner_joint_routes"] = dict(compiler.ROUTE_COUNTS)
        out["miner_joint_device_fetches"] = (
            fused_mod.FETCH_COUNTS["n"] + star_mod.FETCHES["n"] - fetches_before
        )
        miner_s = halo_s + count_s + mine_s
        log(f"miner {miner_s:.0f}s over {universe} halo links "
            f"(halo {halo_s:.0f}s, counting {count_s:.0f}s, joints {mine_s:.0f}s)")
        # phase split in the OUTPUT too: run-to-run spread diagnosis needs
        # to see which phase moved (halo = host CSR walk; counting =
        # count_batch; joints = star folds), not just the merged ratio
        out["miner_halo_s"] = round(halo_s, 1)
        out["miner_counting_s"] = round(count_s, 1)
        out["miner_halo_links"] = universe
        out["miner_candidates"] = n_candidates
        out["miner_total_s"] = round(miner_s, 1)
        # the reference's 74-104 ms/link window covers its per-link
        # template-build + count loop (SimplePatternMiner.ipynb cell 9);
        # the comparable phase here is halo expansion + candidate counting.
        # Whole-KB ngram JOINT mining (miner.mine) is extra work the
        # reference never does at this scale — reported separately.
        out["miner_counting_ms_per_link"] = round(
            (halo_s + count_s) / max(universe, 1) * 1e3, 2
        )
        out["miner_joint_mining_s"] = round(mine_s, 1)
        out["miner_ms_per_link"] = round(miner_s / max(universe, 1) * 1e3, 2)
        out["miner_best_count"] = best.count if best else 0

    # reliability order: the vmapped batch program is the largest payload
    # through a remote-compile tunnel and the most likely to hang there —
    # run it LAST so a hang can't cost the other measurements.  After each
    # measurement the partial dict goes to stdout (last line wins), so the
    # parent keeps everything completed even if it must kill this process.
    # NOTE: batched therefore measures the store AFTER the 10-expression
    # commit (a delta overlay is live) — flagged in the output for
    # cross-round comparability.
    out["batched_after_commit"] = True
    for name, fn in (
        ("sequential", _sequential),
        ("device_only", _device_only),
        ("batched_fresh", _batched_fresh),
        ("commit", _commit),
        ("miner", _miner),
        ("batched", _batched),
    ):
        rem = budget_remaining()
        if rem < 120:
            out[f"{name}_error"] = f"skipped: {rem:.0f}s budget left"
            print(json.dumps(out), flush=True)
            continue
        measure(name, fn)
        print(json.dumps(out), flush=True)
    return out


def run_flybase_subprocess():
    """Run the flybase-scale section in a CHILD process with a hard time
    budget.  The tunnel to remote TPUs occasionally hangs on the largest
    payloads; a hang in-process would block the whole benchmark forever,
    while a child is killable and its streamed partial results (one JSON
    line per completed measurement) survive."""
    import subprocess

    def last_json(captured):
        """Last PARSEABLE json line (a killed child may truncate its final
        print mid-line — walk back to the newest complete one)."""
        if isinstance(captured, bytes):
            captured = captured.decode(errors="replace")
        for line in reversed((captured or "").splitlines()):
            if line.strip().startswith("{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue
        return None

    timeout = float(os.environ.get("DAS_BENCH_FLYBASE_TIMEOUT", "3300"))
    env = dict(os.environ)
    env["DAS_BENCH_DEADLINE"] = str(_START + BUDGET_S - 45)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--flybase-only"],
            capture_output=True, text=True, timeout=timeout, env=env,
        )
        result = last_json(proc.stdout)
        if result is not None:
            if proc.returncode != 0:
                result.setdefault("error", f"exit {proc.returncode}")
            return result
        if proc.returncode != 0:
            # child could not even start measuring (e.g. a runtime whose
            # accelerator lock is per-process-exclusive, unlike the tunnel
            # this isolation was built for): run in-process instead — no
            # hang protection, but correct everywhere
            print(
                f"[bench] flybase child failed (exit {proc.returncode}); "
                "falling back in-process", file=sys.stderr,
            )
            try:
                return flybase_scale_section()
            except Exception as e:
                return {"error": repr(e)}
        return {"error": f"no output (exit {proc.returncode})"}
    except subprocess.TimeoutExpired as e:
        partial = last_json(e.stdout) or {}
        partial["error"] = f"timeout after {timeout:.0f}s (partial results kept)"
        stderr = e.stderr
        if isinstance(stderr, bytes):
            stderr = stderr.decode(errors="replace")
        if stderr:  # how far the child got ([flybase] progress lines)
            partial["stderr_tail"] = stderr.strip().splitlines()[-4:]
        return partial
    except Exception as e:  # subprocess machinery itself failed
        return {"error": repr(e)}


def run_mesh_scaling_subprocess(timeout: float, scale: float):
    """scripts/scaling_bench.py on the virtual CPU mesh (child process —
    the parent holds the TPU).  Returns its final merged JSON line."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    try:
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "scripts", "scaling_bench.py",
                ),
                "--scale", str(scale),
            ],
            capture_output=True, text=True, timeout=timeout, env=env,
        )
        for line in reversed(proc.stdout.splitlines()):
            if line.startswith("{"):
                try:
                    out = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if proc.returncode != 0:
                    # e.g. the collective-shape guard asserting — keep the
                    # traceback tail so the artifact is diagnosable
                    out.setdefault(
                        "error",
                        f"exit {proc.returncode}: "
                        f"{(proc.stderr or '')[-400:]}",
                    )
                return out
        return {"error": f"no output (exit {proc.returncode}): "
                         f"{(proc.stderr or '')[-400:]}"}
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout:.0f}s"}
    except Exception as e:
        return {"error": repr(e)}


def main():
    _enable_proflog()
    # --- head-to-head at reference-feasible scale -------------------------
    sdata, _, _ = build_bio_atomspace(**SMALL)
    host_db = MemoryDB(sdata)
    sdev_db = TensorDB(sdata, DasConfig())
    a_host = PatternMatchingAnswer()
    t0 = time.perf_counter()
    three_var_query().matched(host_db, a_host)
    baseline_s = time.perf_counter() - t0
    a_dev = PatternMatchingAnswer()
    compiler.query_on_device(sdev_db, three_var_query(), a_dev)
    assert a_dev.assignments == a_host.assignments, "result sets diverged"
    small_matches = len(a_host.assignments)
    small_device_s = host_visible_p50(sdev_db, rounds=10)
    vs_baseline = baseline_s / small_device_s if small_device_s > 0 else 0.0
    try:
        small_batch_s, small_bw, _ = batched_per_query(sdev_db)
    except Exception as e:
        print(f"[bench] small batch failed: {e!r}", file=sys.stderr)
        small_batch_s, small_bw = None, 0

    # --- headline: bio-scale KB, device only ------------------------------
    t0 = time.perf_counter()
    ldata, _, _ = build_bio_atomspace(**LARGE)
    build_s = time.perf_counter() - t0
    nodes, links = ldata.count_atoms()
    dev_db = TensorDB(ldata, DasConfig(initial_result_capacity=1 << 16))
    n_matches = compiler.count_matches(dev_db, three_var_query())
    hv_p50 = host_visible_p50(dev_db)
    rtt_ms = transport_rtt_ms()
    n_fetches = fetches_per_query(dev_db)
    # device-only: W DISTINCT grounded 3-clause conjunctions (identical
    # repeats would be collapsed by count_batch's lane dedup in the
    # batched-slope tier)
    all_genes = dev_db.get_all_nodes("Gene", names=True)
    plan_cache = {}

    def grounded_plans(w):
        if w not in plan_cache:
            plan_cache[w] = [
                compiler.plan_query(dev_db, grounded_query(g))
                for g in all_genes[:w]
            ]
        return plan_cache[w]

    try:
        dev_only_ms, dev_only_method = device_only_ms(dev_db, grounded_plans)
    except Exception as e:
        print(f"[bench] device-only measurement failed: {e!r}", file=sys.stderr)
        # degrade honestly: subtract the measured transport from the
        # host-visible figure instead of silently reporting transport
        dev_only_ms = max(hv_p50 * 1e3 - (n_fetches or 1) * rtt_ms, 0.0)
        dev_only_method = "host_visible_minus_rtt"
    p50 = dev_only_ms / 1e3
    matches_per_sec = n_matches / p50 if p50 > 0 else 0.0
    try:
        large_batch_s, large_bw, large_answered = batched_per_query(dev_db)
    except Exception as e:
        print(f"[bench] large batch failed: {e!r}", file=sys.stderr)
        large_batch_s, large_bw, large_answered = None, 0, 0
    # throughput regime: per-query cost keeps halving past width 256
    # (r5 sweep on this KB: 0.73 / 0.46 / 0.35 / 0.33 ms at widths
    # 256/512/1024/2048 — knee ~2048); width 1024 is the recorded
    # wide point (4x less lane memory than the knee, ~95% of the win)
    try:
        wide_batch_s, wide_bw, _ = batched_per_query(
            dev_db, width=int(os.environ.get("DAS_BENCH_BATCH_WIDE", "1024")),
            rounds=3,
            verify=large_batch_s is None,  # width-256 already proved parity
        )
    except Exception as e:
        print(f"[bench] wide batch failed: {e!r}", file=sys.stderr)
        wide_batch_s, wide_bw = None, 0
    try:
        served_p50, served_per_query, served_stats = served_latency(dev_db)
    except Exception as e:
        print(f"[bench] served measurement failed: {e!r}", file=sys.stderr)
        served_p50 = served_per_query = served_stats = None
    # serving-throughput record (ISSUE 2): coalescer qps with pipelining
    # on/off + result-cache hit rate and cache-vs-device latency
    try:
        serving = _with_programs(serving_throughput, dev_db)
    except Exception as e:
        print(f"[bench] serving throughput failed: {e!r}", file=sys.stderr)
        serving = {"error": repr(e)[:200]}
    # chaos serving (ISSUE 13): open-loop qps at a fixed injected fault
    # rate (degraded-qps ratio), deadline-miss rate under injected
    # latency, and the breaker trip→probe→restore time
    try:
        chs = _with_programs(chaos_serving, dev_db)
    except Exception as e:
        print(f"[bench] chaos serving failed: {e!r}", file=sys.stderr)
        chs = {"error": repr(e)[:200]}
    # Pallas kernel A/B (VERDICT r05 depth item): fused 3-var count via
    # the kernel route vs the lowered op chain, plus the staged pipeline's
    # dispatched-ops count both ways (on the small KB — the count is
    # shape-independent)
    try:
        ab = _with_programs(kernel_ab, dev_db)
    except Exception as e:
        print(f"[bench] kernel A/B failed: {e!r}", file=sys.stderr)
        ab = {"error": repr(e)[:200]}
    try:
        ab["staged_dispatches"] = staged_dispatch_counts(sdev_db)
    except Exception as e:
        print(f"[bench] staged dispatch count failed: {e!r}", file=sys.stderr)
        ab["staged_dispatches"] = {"error": repr(e)[:200]}
    # grid-chunked kernel A/B at a >2^18-row synthetic term (ISSUE 4):
    # the shapes the old single-block row bound kicked to the lowered
    # ops; includes the no-silent-fallback dispatch assertion
    try:
        tiled_ab = _with_programs(tiled_kernel_ab)
    except Exception as e:
        print(f"[bench] tiled kernel A/B failed: {e!r}", file=sys.stderr)
        tiled_ab = {"error": repr(e)[:200]}
    # sharded serving parity (ISSUE 3): mesh-path pipelined-vs-serial qps
    # A/B plus the count_many kernel A/B, on the small KB (the mesh
    # partition and the vmapped count groups are cheap at that scale)
    try:
        shs = _with_programs(sharded_serving, sdata, sdev_db)
    except Exception as e:
        print(f"[bench] sharded serving failed: {e!r}", file=sys.stderr)
        shs = {"error": repr(e)[:200]}
    # cost-based planner A/B (ISSUE 8): planner-vs-greedy on skew-heavy
    # FlyBase-shape fan-out terms — wall ms, compiled program counts,
    # retry rounds avoided, parity
    try:
        pab = _with_programs(planner_ab)
    except Exception as e:
        print(f"[bench] planner A/B failed: {e!r}", file=sys.stderr)
        pab = {"error": repr(e)[:200]}
    # multiway join A/B (ISSUE 9): planner-routed k-way intersection vs
    # the binary chain on the skew-heavy hub fan-out star — programs,
    # retry tiers avoided, warm ms, bit-parity
    try:
        mab = _with_programs(multiway_ab)
    except Exception as e:
        print(f"[bench] multiway A/B failed: {e!r}", file=sys.stderr)
        mab = {"error": repr(e)[:200]}
    # whole-tree fused execution A/B (ISSUE 10): one program per
    # N-branch Or vs the tree executor's per-site composites — program
    # counts, time-to-answer, bit-parity asserted in-bench
    try:
        tfab = _with_programs(tree_fused_ab)
    except Exception as e:
        print(f"[bench] tree-fused A/B failed: {e!r}", file=sys.stderr)
        tfab = {"error": repr(e)[:200]}
    # durability record (ISSUE 15): verified restore vs full rebuild,
    # WAL replay throughput, chaos-recovery wall time — parity asserted
    # in-bench; runs LAST against dev_db (its commits mutate the store)
    try:
        dur = _with_programs(durability_section, dev_db)
    except Exception as e:
        print(f"[bench] durability failed: {e!r}", file=sys.stderr)
        dur = {"error": repr(e)[:200]}
    # release before the flybase-scale build (~40 GB host): the executor
    # cache forms a db->dev->executor->db cycle, so collect explicitly
    del dev_db, ldata
    import gc

    gc.collect()

    result = {
        "metric": "bio_atomspace 3-var conjunctive query latency (device-only)",
        "value": round(dev_only_ms, 3),
        "unit": "ms",
        "vs_baseline": round(vs_baseline, 1),
        "extra": {
            "platform": jax.devices()[0].platform,
            "device": str(jax.devices()[0]),
            "workload": LARGE,       # cross-run comparability (ADVICE r1)
            "rounds": ROUNDS,
            # --- latency decomposition (VERDICT r02 item 3) --------------
            # value = device compute per query, measured as the width
            # slope of single-dispatch fori_loop count programs (one fetch
            # regardless of width — immune to the tunnel RTT).  The r01
            # (117.5 ms) and r02 (232.8 ms) headline `value`s were
            # HOST-VISIBLE timings of the same query: transport dominated
            # them (r02 == fetches_per_query x transport_rtt + device; the
            # r01->r02 doubling tracked the tunnel round trips, not device
            # work).  host_visible_p50_ms continues that series.
            "host_visible_p50_ms": round(hv_p50 * 1e3, 3),
            "transport_rtt_ms": round(rtt_ms, 3),
            "fetches_per_query": n_fetches,
            # "loop" = sequential fori_loop width slope (exact);
            # "batched_slope" = vmapped count_batch width slope (device
            # compute per query in the batched regime);
            # "host_visible_minus_rtt" = subtraction estimate
            "device_only_method": dev_only_method,
            # value measures W distinct grounded 3-clause conjunctions
            # (the serving-shaped family); the all-variable analytic query
            # is tracked by host_visible_p50_ms + batched_ms_per_query
            "device_only_query": "grounded 3-clause conjunction",
            "kb_nodes": nodes,
            "kb_links": links,
            "kb_build_s": round(build_s, 2),
            "matches": n_matches,
            "pattern_matches_per_sec": round(matches_per_sec),
            "baseline_config": SMALL,
            "baseline_s": round(baseline_s, 3),
            "baseline_matches": small_matches,
            "small_device_p50_ms": round(small_device_s * 1e3, 3),
            "baseline_model": "reference Python algebra on in-memory store",
            # per-query latency at batch width (vmapped count_batch over
            # distinct grounded 3-clause queries) — the serving-shaped
            # number; reference warm-probe budget is 0.097-0.131 ms/probe.
            # null = the measurement failed (see stderr), NOT a fast run
            "batched_ms_per_query": (
                None if large_batch_s is None else round(large_batch_s * 1e3, 3)
            ),
            "batch_width": large_bw,
            "batch_answered": large_answered,
            # the throughput-regime point (see comment at measurement)
            "batched_wide_ms_per_query": (
                None if wide_batch_s is None else round(wide_batch_s * 1e3, 3)
            ),
            "batch_width_wide": wide_bw,
            "small_batched_ms_per_query": (
                None if small_batch_s is None else round(small_batch_s * 1e3, 3)
            ),
            "small_batch_width": small_bw,
            # serving edge under 16 concurrent clients (coalesced singles,
            # full query materialization incl. transport): per-query cost
            # must beat one tunnel RTT — see transport_rtt_ms above
            "served_p50_ms": (
                None if served_p50 is None else round(served_p50, 2)
            ),
            "served_ms_per_query": (
                None if served_per_query is None else round(served_per_query, 2)
            ),
            "served_stats": served_stats,
            # serving throughput under the coalescer (ISSUE 2):
            # {serial_qps, pipelined_qps, pipeline_depth, cache_hit_rate,
            #  cache_hit_ms, device_path_ms, cache_speedup, ...} — the
            # pipelining A/B runs cache-off so both arms pay device work
            "serving": serving,
            # chaos serving (ISSUE 13): {clean_qps, chaos_qps,
            # chaos_qps_ratio, typed_errors, injected (per-site),
            # deadline_miss_rate @ deadline_ms, breaker_trips/
            # recoveries/recovery_ms, fault_spec, interpret honesty
            # flag} — every failure typed, answers chaos-parity clean
            "chaos": chs,
            # sharded serving parity (ISSUE 3): mesh-path open-loop qps
            # A/B {serial_qps, pipelined_qps, inflight_peak, n_shards} +
            # count_many kernel A/B {count_lowered_ms, count_kernel_ms,
            # count_kernel_engaged, count_parity}
            "sharded_serving": shs,
            # kernel-vs-lowered A/B: {lowered_ms, kernel_ms, interpret,
            # route, staged_dispatches: {lowered, kernel}}.  interpret=
            # true means the kernels ran through the Pallas interpreter
            # (CPU-only run) — recorded, not a perf claim
            "kernel_ab": ab,
            # grid-chunked A/B at a >2^18-row synthetic term:
            # {tiled_route, probe/join kernel-vs-lowered ms,
            #  tiled_vs_lowered_ms, parity, no_lowered_fallback,
            #  interpret honesty flag} (ISSUE 4)
            "tiled_kernel_ab": tiled_ab,
            # cost-based planner A/B (ISSUE 8): {planner_ms, greedy_ms,
            # planner/greedy first-contact ms + program counts,
            # retry_rounds_avoided, planner_route, parity,
            # planner_stats (est-vs-actual telemetry)}
            "planner_ab": pab,
            # multiway join A/B (ISSUE 9): {multiway_ms, chain_ms,
            # first-contact ms + program counts per arm,
            # chain_retry_rounds_avoided, multiway_route, parity,
            # multiway_stats (est-vs-actual), interpret honesty flag}
            "multiway_ab": mab,
            # whole-tree fused execution A/B (ISSUE 10): {fused_ms,
            # tree_ms, first-contact ms + device program counts per arm,
            # tree_programs_avoided, tree_fused_route, parity, interpret
            # honesty flag} — caches off, the per-branch dispatch/settle
            # cost is the thing under test
            "tree_fused_ab": tfab,
            # durability (ISSUE 15): {snapshot_s, restore_s, rebuild_s,
            # restore_vs_rebuild, wal_records_replayed,
            # wal_replay_commits_per_s, chaos_recovery_ms, interpret
            # honesty flag} — restore/chaos answers parity-asserted
            # in-bench
            "durability": dur,
            # program ledger snapshot (ISSUE 14): XLA compiles observed
            # across the whole run, total/cold-start compile seconds,
            # ledger hit rate, and the per-site byte-model calibration
            # aggregate (budget_vs_actual) — the device-side compile
            # story the per-section programs_compiled/compile_s fields
            # decompose
            "programs": proflog.snapshot(),
            "flybase_scale": None,
        },
    }
    # the headline survives NO MATTER what the flybase section does: print
    # it now, then print the merged line after (last parseable line wins).
    # The compact form prints too: if the driver kills this process during
    # the flybase child, the 2000-char tail must still contain one
    # COMPLETE parseable line (the full headline alone is ~2.2 KB)
    print(json.dumps(result), flush=True)
    # full_record=None: BENCH_FULL.json has not been written THIS run yet
    print(json.dumps(compact_headline(result, None)), flush=True)

    # --- flybase-scale proof (skippable: DAS_BENCH_FLYBASE=0; default on
    # for accelerator runs, off on CPU where the 27.9M-link KB is hostile)
    on_accel = jax.devices()[0].platform != "cpu"
    if os.environ.get("DAS_BENCH_FLYBASE", "1" if on_accel else "0") == "1":
        rem = budget_remaining() - 60  # leave room for the final print
        if rem < 300:
            flybase = {
                "error": f"skipped: {rem:.0f}s left of {BUDGET_S:.0f}s budget"
            }
        else:
            if "DAS_BENCH_FLYBASE_SCALE" not in os.environ:
                # auto-scale the KB to the remaining budget; the full
                # 27.9M-link build needs ~20-25 min incl. measurements
                scale = 1.0 if rem > 1500 else (0.3 if rem > 700 else 0.1)
                os.environ["DAS_BENCH_FLYBASE_SCALE"] = str(scale)
            os.environ["DAS_BENCH_FLYBASE_TIMEOUT"] = str(
                min(
                    float(os.environ.get("DAS_BENCH_FLYBASE_TIMEOUT", "3300")),
                    rem,
                )
            )
            flybase = run_flybase_subprocess()
            if isinstance(flybase, dict):
                flybase.setdefault(
                    "flybase_scale_factor",
                    float(os.environ["DAS_BENCH_FLYBASE_SCALE"]),
                )
        result["extra"]["flybase_scale"] = flybase
    # --- mesh scaling table (VERDICT r04 item 4): 1/2/4/8-shard timings +
    # per-shard buffer guard on the virtual CPU mesh, in a child process.
    # Runs on leftover budget only — flybase keeps priority; the full-scale
    # table lives in ROUND5.md from a dedicated run.
    if os.environ.get("DAS_BENCH_MESH", "1") == "1":
        rem = budget_remaining() - 90
        if rem < 240:
            result["extra"]["mesh_scaling"] = {
                "error": f"skipped: {rem:.0f}s left"
            }
        else:
            result["extra"]["mesh_scaling"] = run_mesh_scaling_subprocess(
                timeout=rem,
                scale=float(os.environ.get(
                    "DAS_BENCH_MESH_SCALE", "0.3" if rem > 500 else "0.1"
                )),
            )
    # full merged record -> file (judge artifact) + stdout (human record);
    # then the COMPACT headline prints LAST.  The driver keeps only the
    # final ~2000 chars of stdout and parses the last complete JSON line:
    # r03/r04 were unparseable because the merged line alone is ~2.5 KB.
    full_record = "BENCH_FULL.json"
    try:
        with open(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         full_record), "w",
        ) as f:
            json.dump(result, f, indent=1)
    except OSError as e:
        print(f"[bench] BENCH_FULL.json write failed: {e!r}", file=sys.stderr)
        full_record = None  # never advertise a stale file from a prior run
    print(json.dumps(result), flush=True)
    print(json.dumps(compact_headline(result, full_record)), flush=True)


def compact_headline(result, full_record="BENCH_FULL.json"):
    """North-star subset of the merged record, guaranteed < 1.5 KB, printed
    as the FINAL stdout line so the driver's 2000-char tail always contains
    one complete parseable JSON line (VERDICT r04 item 1)."""
    ex = result.get("extra", {})
    fb = ex.get("flybase_scale") or {}
    fb_err = fb.get("error")
    # 16 (was 24, 40, 48, 64, 128): the durability headline (ISSUE 15,
    # after ISSUE 13's chaos fields) consumed the compact line's
    # remaining headroom — the full untruncated error stays in
    # BENCH_FULL.json either way (platform, served_ms_per_query,
    # flybase commit10_steady_s / sequential_p50_ms / batched_fresh_ms
    # / batched_ms_per_query moved to the full record for the same
    # reason: none was pinned, all are derivable context; the
    # 16-client served figure is superseded by open_loop_ms_per_query
    # anyway)
    if isinstance(fb_err, str) and len(fb_err) > 16:
        fb_err = fb_err[:16]
    compact = {
        "metric": result["metric"],
        "value": result["value"],
        "unit": result["unit"],
        "vs_baseline": result["vs_baseline"],
        "extra": {
            "host_visible_p50_ms": ex.get("host_visible_p50_ms"),
            "transport_rtt_ms": ex.get("transport_rtt_ms"),
            "batched_ms_per_query": ex.get("batched_ms_per_query"),
            # 256-client open-loop serving (ISSUE 6): wall ms/query in
            # the pipelined arm, time until the FIRST client's rows
            # landed (streaming early-settle), and the adaptive window
            # depth the worker actually reached
            "open_loop_ms_per_query": (
                (ex.get("serving") or {}).get("served_ms_per_query")
            ),
            "time_to_first_row_ms": (
                (ex.get("serving") or {}).get("time_to_first_row_ms")
            ),
            # tail of the open-loop latency distribution (ISSUE 12):
            # derived from the obs histogram layer's fixed log buckets
            # (full record carries p50/p95 + the bucket vectors)
            "open_loop_p99_ms": (
                (ex.get("serving") or {}).get("open_loop_p99_ms")
            ),
            "effective_depth": (ex.get("serving") or {}).get(
                "effective_depth"
            ),
            # serving-throughput headline (ISSUE 2): coalescer qps
            # [pipelined(depth=2), serial(depth=1)], the depth, and the
            # result-cache record [hit rate, hit ms, device-path ms]
            "serving_qps": [
                (ex.get("serving") or {}).get("pipelined_qps"),
                (ex.get("serving") or {}).get("serial_qps"),
            ],
            "pipeline_depth": (ex.get("serving") or {}).get("pipeline_depth"),
            "cache_hit_rate": (ex.get("serving") or {}).get("cache_hit_rate"),
            "cache_vs_device_ms": [
                (ex.get("serving") or {}).get("cache_hit_ms"),
                (ex.get("serving") or {}).get("device_path_ms"),
            ],
            # sharded serving parity (ISSUE 3): mesh-path open-loop qps
            # [pipelined(depth=2), serial(depth=1)] and the count-batch
            # kernel A/B [kernel_ms, lowered_ms]
            "sharded_qps": [
                (ex.get("sharded_serving") or {}).get("pipelined_qps"),
                (ex.get("sharded_serving") or {}).get("serial_qps"),
            ],
            "count_kernel_vs_lowered_ms": [
                (ex.get("sharded_serving") or {}).get("count_kernel_ms"),
                (ex.get("sharded_serving") or {}).get("count_lowered_ms"),
            ],
            # Pallas route record: which kernel route ran, and the A/B
            # [kernel_ms, lowered_ms] (interpret runs flagged in the full
            # record's kernel_ab.interpret)
            "kernel_route": (ex.get("kernel_ab") or {}).get("route"),
            "kernel_vs_lowered_ms": [
                (ex.get("kernel_ab") or {}).get("kernel_ms"),
                (ex.get("kernel_ab") or {}).get("lowered_ms"),
            ],
            # grid-chunked route at the >2^18-row synthetic term (ISSUE
            # 4): the planner verdict and [kernel_ms, lowered_ms] summed
            # over the probe+join arms (interpret flag in the full
            # record's tiled_kernel_ab)
            "tiled_route": (ex.get("tiled_kernel_ab") or {}).get("route"),
            "tiled_vs_lowered_ms": (
                (ex.get("tiled_kernel_ab") or {}).get("tiled_vs_lowered_ms")
                or [None, None]
            ),
            # cost-based planner A/B (ISSUE 8): the route the planner
            # chose for the hub fan-out term, warm per-query ms
            # [planner, greedy], and the capacity-retry tiers (= XLA
            # compiles) the costed seeds eliminated on first contact
            "planner_route": (ex.get("planner_ab") or {}).get(
                "planner_route"
            ),
            "planner_vs_greedy_ms": [
                (ex.get("planner_ab") or {}).get("planner_ms"),
                (ex.get("planner_ab") or {}).get("greedy_ms"),
            ],
            "retry_rounds_avoided": (ex.get("planner_ab") or {}).get(
                "retry_rounds_avoided"
            ),
            # multiway join A/B (ISSUE 9): the route the planner chose
            # for the skew-heavy hub fan-out star, warm per-query ms
            # [multiway, chain], and the capacity-retry tiers (= XLA
            # compiles) the k-way intersection's exact seed eliminated
            "multiway_route": (ex.get("multiway_ab") or {}).get(
                "multiway_route"
            ),
            "multiway_vs_chain_ms": [
                (ex.get("multiway_ab") or {}).get("multiway_ms"),
                (ex.get("multiway_ab") or {}).get("chain_ms"),
            ],
            "chain_retry_rounds_avoided": (ex.get("multiway_ab") or {}).get(
                "chain_retry_rounds_avoided"
            ),
            # whole-tree fused execution A/B (ISSUE 10): the planner's
            # whole-tree route, warm per-query ms [fused, tree], and the
            # per-site device programs (= dispatch/settle round trips)
            # the one-program route eliminated on the 3-branch Or suite
            "tree_fused_route": (ex.get("tree_fused_ab") or {}).get(
                "tree_fused_route"
            ),
            "tree_fused_vs_tree_ms": [
                (ex.get("tree_fused_ab") or {}).get("fused_ms"),
                (ex.get("tree_fused_ab") or {}).get("tree_ms"),
            ],
            "tree_programs_avoided": (ex.get("tree_fused_ab") or {}).get(
                "tree_programs_avoided"
            ),
            # chaos serving headline (ISSUE 13): open-loop qps under a
            # fixed injected fault rate as a fraction of the fault-free
            # run, and the breaker recoveries observed (full record
            # carries the per-site injection counts, deadline-miss rate
            # and recovery wall time)
            "chaos_qps_ratio": (ex.get("chaos") or {}).get(
                "chaos_qps_ratio"
            ),
            "breaker_recoveries": (ex.get("chaos") or {}).get(
                "breaker_recoveries"
            ),
            # durability headline (ISSUE 15): verified warm-restore wall
            # seconds — snapshot + WAL replay + warm bundle (the full
            # record's `durability` carries the rebuild arm, replay
            # throughput and chaos-recovery wall time)
            "restore_s": (ex.get("durability") or {}).get("restore_s"),
            # program-ledger headline (ISSUE 14): total XLA compile
            # seconds the run paid (per-section decomposition + the
            # cost/memory analysis live in the full record's `programs`
            # and per-section programs_compiled/compile_s fields)
            "compile_s": (ex.get("programs") or {}).get("compile_s"),
            "kb_nodes": ex.get("kb_nodes"),
            "kb_links": ex.get("kb_links"),
            "matches": ex.get("matches"),
            "flybase": None if not fb else {
                "kb_links": fb.get("kb_links"),
                "scale": fb.get("flybase_scale_factor"),
                "ingest_expr_per_s": fb.get("ingest_expressions_per_s"),
                "device_only_ms": fb.get("sequential_device_only_ms"),
                "miner_ms_per_link": fb.get("miner_ms_per_link"),
                "error": fb_err,
            },
            "full_record": full_record,
        },
    }
    line = json.dumps(compact)
    if len(line) > 1500:  # belt-and-braces: drop to the bare driver contract
        compact = {k: compact[k] for k in
                   ("metric", "value", "unit", "vs_baseline")}
    return compact


if __name__ == "__main__":
    if "--flybase-only" in sys.argv:
        flybase_scale_section()
    else:
        main()
