#!/usr/bin/env python
"""Load a MeTTa knowledge base and inspect it — script form of the
reference walkthrough notebook (/root/reference/notebooks/
LoadKnowledgeBase.ipynb): load `data/samples/animals.metta`, print atom
counts, look atoms up by handle and by name.

Run:  python examples/load_knowledge_base.py [path/to/kb.metta]
"""

import sys

sys.path.insert(0, ".")

from das_tpu.api.atomspace import DistributedAtomSpace, QueryOutputFormat


def main() -> None:
    source = sys.argv[1] if len(sys.argv) > 1 else "data/samples/animals.metta"
    das = DistributedAtomSpace(backend="memory")
    das.load_knowledge_base(source)

    nodes, links = das.count_atoms()
    print(f"loaded {source}: {nodes} nodes, {links} links")

    human = das.get_node("Concept", "human")
    print("Concept:human handle =", human)
    print("as dict =", das.get_atom(human, output_format=QueryOutputFormat.ATOM_INFO))

    print("\nall Inheritance links:")
    for link in das.get_links("Inheritance", output_format=QueryOutputFormat.ATOM_INFO):
        print(" ", link)

    print("\nnodes named like 'mon':")
    for handle in das.get_nodes("Concept", output_format=QueryOutputFormat.HANDLE):
        name = das.get_node_name(handle)
        if "mon" in name:
            print(" ", handle, name)


if __name__ == "__main__":
    main()
