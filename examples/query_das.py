#!/usr/bin/env python
"""Pattern-matching queries over the animals KB — script form of the
reference notebook /root/reference/notebooks/QueryDAS.ipynb: the same four
And/Not/Or example queries plus an assignment printer.

Run:  python examples/query_das.py
"""

import sys

sys.path.insert(0, ".")

from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.models.animals import animals_metta
from das_tpu.query.ast import And, Link, Node, Not, Or, Variable


def show(das, title, query):
    print(f"\n== {title}")
    matched, answer = das.query_answer(query)
    if not matched:
        print("  no match")
        return
    for assignment in sorted(answer.assignments, key=repr):
        print("  ", assignment)


def main() -> None:
    das = DistributedAtomSpace(backend="tensor")
    das.load_metta_text(animals_metta())

    # 1. What inherits from mammal?
    show(
        das,
        "Inheritance($V1, mammal)",
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
    )

    # 2. Similar to human AND an Inheritance exists for the same V1
    show(
        das,
        "And(Similarity(human, $V1), Inheritance($V1, $V2))",
        And([
            Link("Similarity", [Node("Concept", "human"), Variable("V1")], False),
            Link("Inheritance", [Variable("V1"), Variable("V2")], True),
        ]),
    )

    # 3. Similar to human but NOT a mammal
    show(
        das,
        "And(Similarity(human, $V1), Not(Inheritance($V1, mammal)))",
        And([
            Link("Similarity", [Node("Concept", "human"), Variable("V1")], False),
            Not(Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)),
        ]),
    )

    # 4. Inherits from reptile OR from plant
    show(
        das,
        "Or(Inheritance($V1, reptile), Inheritance($V1, plant))",
        Or([
            Link("Inheritance", [Variable("V1"), Node("Concept", "reptile")], True),
            Link("Inheritance", [Variable("V1"), Node("Concept", "plant")], True),
        ]),
    )


if __name__ == "__main__":
    main()
