#!/usr/bin/env python
"""Timed Execution-link queries over a bio-scale KB — script form of the
reference notebook /root/reference/notebooks/QueryFlyBase.ipynb (Execution
link templates with WallClock timing).  The private FlyBase dump isn't
redistributable, so the synthetic bio atomspace (das_tpu/models/bio.py,
same schema/shape as scripts/benchmark.py's queries) stands in; pass a
.metta path produced by the flybase converter to use real data.

Run:  python examples/query_flybase.py [flybase.metta]
"""

import sys
import time

sys.path.insert(0, ".")

from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.query.ast import And, Link, Variable
from das_tpu.utils.timing import Clock


def main() -> None:
    das = DistributedAtomSpace(backend="tensor")
    if len(sys.argv) > 1:
        das.load_canonical_knowledge_base(sys.argv[1])
    else:
        data, genes, _ = build_bio_atomspace(
            n_genes=2000, n_processes=200, members_per_gene=5,
            n_interactions=1500, n_evaluations=500,
        )
        das.db.data = data
        das._refresh()
    nodes, links = das.count_atoms()
    print(f"KB: {nodes} nodes, {links} links")

    clock = Clock()
    queries = {
        "Member($gene, $process)": Link(
            "Member", [Variable("gene"), Variable("process")], True
        ),
        "two genes in one process": And([
            Link("Member", [Variable("g1"), Variable("p")], True),
            Link("Member", [Variable("g2"), Variable("p")], True),
        ]),
        "co-process + interaction": And([
            Link("Member", [Variable("g1"), Variable("p")], True),
            Link("Member", [Variable("g2"), Variable("p")], True),
            Link("Interacts", [Variable("g1"), Variable("g2")], True),
        ]),
    }
    for title, query in queries.items():
        clock.start()
        matched, answer = das.query_answer(query)
        elapsed_ms = clock.elapsed() * 1e3
        print(f"{title}: {len(answer.assignments)} assignments in {elapsed_ms:.1f} ms")
        t0 = time.perf_counter()
        das.query_answer(query)
        print(f"  warm repeat: {(time.perf_counter() - t0) * 1e3:.1f} ms")


if __name__ == "__main__":
    main()
