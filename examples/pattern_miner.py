#!/usr/bin/env python
"""Frequent-subgraph mining — script form of the reference notebook
/root/reference/notebooks/SimplePatternMiner.ipynb: halo expansion around
seed nodes, wildcard candidate patterns with support counts, stochastic
I-Surprisingness mining.  All counting runs through the batched device
path (one vmapped program per pattern shape) instead of the notebook's one
Redis probe per candidate.

Run:  python examples/pattern_miner.py
"""

import sys
import time

sys.path.insert(0, ".")

from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.mining.miner import PatternMiner
from das_tpu.models.bio import build_bio_atomspace


def main() -> None:
    das = DistributedAtomSpace(backend="tensor")
    data, genes, _ = build_bio_atomspace(
        n_genes=500, n_processes=50, members_per_gene=5,
        n_interactions=400, n_evaluations=100,
    )
    das.db.data = data
    das._refresh()
    nodes, links = das.count_atoms()
    print(f"KB: {nodes} nodes, {links} links")

    miner = PatternMiner(das.db, halo_length=2, link_rate=0.05, support=2, seed=7)

    t0 = time.perf_counter()
    universe = miner.expand_halo(genes[:20])
    print(f"halo: {universe} links in {time.perf_counter() - t0:.2f}s")

    t0 = time.perf_counter()
    n_candidates = miner.build_patterns()
    print(f"candidates: {n_candidates} in {time.perf_counter() - t0:.2f}s")

    t0 = time.perf_counter()
    best = miner.mine(ngram=2, epochs=200)
    print(f"mined in {time.perf_counter() - t0:.2f}s")
    if best:
        print("best pattern:", best.pattern)
        print("count:", best.count, " isurprisingness:", round(best.isurprisingness, 4))


if __name__ == "__main__":
    main()
