// RFC 1321 MD5, clean-room implementation from the specification.
// Used by the native canonical scanner so content-addressed handles are
// byte-for-byte identical to the Python path (das_tpu/core/hashing.py,
// reference /root/reference/das/expression_hasher.py:4-35).
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>

// Writes the 32-char lowercase hex digest of data[0..len) into out.
void md5_hex(const char* data, size_t len, char out[32]);

inline std::string md5_hex_str(const std::string& s) {
  std::string out(32, '0');
  md5_hex(s.data(), s.size(), &out[0]);
  return out;
}
