// Columnar canonical knowledge-base scanner (round-4 ingest path).
//
// The record-stream scanner (das_native.cc) parallelizes across FILES and
// leaves decode to a single-threaded Python byte loop — the measured
// bottleneck at reference scale (27.9M expressions, ~21k expr/s end to
// end).  This module goes columnar end to end:
//
//   1. each input file is split at newline boundaries into chunks, parsed
//      on a work-stealing thread pool (md5 + expression parsing is the
//      dominant cost and is embarrassingly parallel once the canonical
//      section ordering — typedefs < terminals < expressions, see the
//      reference's canonical assumptions at
//      /root/reference/das/distributed_atom_space.py:366-402 — is
//      validated per chunk + at the merge seam);
//   2. a single-threaded merge dedups records in (file, chunk) order with
//      an open-addressing map over the 128-bit digests and assigns dense
//      node/link indices (first occurrence wins, matching Python dict
//      insertion semantics);
//   3. link elements are resolved to those indices in a second pass
//      (declaration position never matters, exactly like the Python
//      finalize's row_of_hex resolution) — unresolved elements become -1
//      with their hex recorded for the dangling set.
//
// Output is a set of flat arrays Python wraps as numpy columns with ZERO
// per-record Python work: type pool (names + md5), typedef columns, node
// columns (hash16, type id, name blob+offsets), link columns (hash16,
// ct_hash16, type id, toplevel, element offsets + resolved indices).
//
// Element index encoding: node i -> i; link j -> n_nodes + j; dangling -> -1.
//
// Known (documented) strictness deltas vs the state-machine scanner, all on
// malformed input only: a typedef-shaped "(:" line appearing AFTER the
// terminals section is an error here (the reference's machine silently
// parses it as a terminal named like a type); out-of-order sections report
// a seam error naming the chunk rather than the exact line.

#include <atomic>
#include <cctype>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <memory>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "md5.h"

namespace {

struct ColParseError {
  std::string msg;
  explicit ColParseError(std::string m) : msg(std::move(m)) {}
};

// -- small string helpers (Python str semantics, same as das_native.cc) ----

std::string c_strip(const std::string& s) {
  size_t a = 0, b = s.size();
  while (a < b && std::isspace((unsigned char)s[a])) a++;
  while (b > a && std::isspace((unsigned char)s[b - 1])) b--;
  return s.substr(a, b - a);
}

std::vector<std::string> c_split_ws(const std::string& s) {
  std::vector<std::string> out;
  size_t i = 0, n = s.size();
  while (i < n) {
    while (i < n && std::isspace((unsigned char)s[i])) i++;
    size_t j = i;
    while (j < n && !std::isspace((unsigned char)s[j])) j++;
    if (j > i) out.push_back(s.substr(i, j - i));
    i = j;
  }
  return out;
}

std::string c_rstrip_paren(const std::string& s) {
  size_t b = s.size();
  while (b > 0 && s[b - 1] == ')') b--;
  return s.substr(0, b);
}

std::string c_strip_quotes(const std::string& s) {
  size_t a = 0, b = s.size();
  while (a < b && s[a] == '"') a++;
  while (b > a && s[b - 1] == '"') b--;
  return s.substr(a, b - a);
}

std::string c_composite_hash(const std::vector<std::string>& parts) {
  if (parts.size() == 1) return parts[0];
  // thread-local scratch: this runs once or twice per expression and a
  // fresh allocation per call dominated the single-core parse profile
  thread_local std::string joined;
  joined.clear();
  for (size_t i = 0; i < parts.size(); i++) {
    if (i) joined.push_back(' ');
    joined += parts[i];
  }
  std::string out = md5_hex_str(joined);
  if (joined.capacity() > (1u << 16)) {
    // pathological-arity lines must not pin MBs for the thread lifetime
    joined.clear();
    joined.shrink_to_fit();
  }
  return out;
}

inline void hex2bin(const char* hex, uint8_t out[16]) {
  auto nib = [](char c) -> uint8_t {
    return c <= '9' ? (uint8_t)(c - '0') : (uint8_t)(c - 'a' + 10);
  };
  for (int i = 0; i < 16; i++)
    out[i] = (uint8_t)((nib(hex[2 * i]) << 4) | nib(hex[2 * i + 1]));
}

// -- per-chunk output -------------------------------------------------------

// line classes (section ordering): 1=typedef 2=terminal 3=expression
struct LocalCols {
  // local type pool, first-occurrence order
  std::unordered_map<std::string, int32_t> tid_of;
  std::vector<std::string> type_names;
  std::string type_hash_hex;  // 32 chars per local tid

  std::vector<int32_t> td_name_tid, td_stype_tid;
  std::string td_hex;  // per record: 32B ct_hash + 32B hash_code

  std::vector<int32_t> term_tid;
  std::string term_hex;  // 32 chars per terminal (terminal hash)
  std::string name_blob;
  std::vector<uint64_t> name_end;  // end offset in name_blob per terminal

  std::vector<int32_t> link_tid;
  std::string link_hex;  // per link: 32B ct_hash + 32B hash_code
  std::vector<uint8_t> link_top;
  std::vector<uint32_t> link_ne;
  std::string elem_hex;  // 32 chars per element, flat in link order

  uint8_t first_class = 0, last_class = 0;
  bool saw_terminal = false, saw_expression = false;
  bool expr_before_terminal = false;
  std::string error;
};

class ChunkScanner {
 public:
  LocalCols out;

  ChunkScanner() {
    mark_hash_ = md5_hex_str(":");
    base_hash_ = md5_hex_str("Type");
  }

  void parse(const char* text, size_t len, const std::string& origin,
             long first_lineno) {
    long lineno = first_lineno - 1;
    size_t pos = 0;
    while (pos <= len) {
      size_t nl = pos;
      while (nl < len && text[nl] != '\n') nl++;
      lineno++;
      // pointer-based: a per-line std::string was ~30M allocations per
      // reference-scale file on the single-core parse path
      process_line(text + pos, nl - pos, lineno, origin);
      if (nl >= len) break;
      pos = nl + 1;
    }
  }

 private:
  std::string mark_hash_, base_hash_;

  int32_t local_tid(const std::string& name) {
    auto it = out.tid_of.find(name);
    if (it != out.tid_of.end()) return it->second;
    int32_t tid = (int32_t)out.type_names.size();
    out.tid_of.emplace(name, tid);
    out.type_names.push_back(name);
    out.type_hash_hex += md5_hex_str(name);
    return tid;
  }

  const char* tid_hash(int32_t tid) const {
    return out.type_hash_hex.data() + 32 * (size_t)tid;
  }

  static std::string terminal_hash(const std::string& type, const std::string& name) {
    thread_local std::string s;
    s.clear();
    s += type;
    s.push_back(' ');
    s += name;
    std::string out = md5_hex_str(s);
    if (s.capacity() > (1u << 16)) {
      s.clear();
      s.shrink_to_fit();
    }
    return out;
  }

  [[noreturn]] static void fail(const std::string& origin, long lineno,
                                const std::string& line, const std::string& reason) {
    throw ColParseError(origin + ": line " + std::to_string(lineno) + ": " +
                        reason + ": " + line);
  }

  [[noreturn]] static void fail(const std::string& origin, long lineno,
                                const char* b, size_t n,
                                const std::string& reason) {
    fail(origin, lineno, std::string(b, n), reason);
  }

  void note_class(uint8_t cls, const std::string& origin, long lineno,
                  const char* b, size_t n) {
    if (!out.first_class) out.first_class = cls;
    if (cls < out.last_class)
      fail(origin, lineno, b, n,
           cls == 1 ? "typedef after terminals/expressions"
                    : "terminal after expressions");
    out.last_class = cls;
    if (cls == 2) out.saw_terminal = true;
    if (cls == 3) {
      if (!out.saw_terminal && !out.saw_expression)
        out.expr_before_terminal = true;
      out.saw_expression = true;
    }
  }

  void emit_typedef(const std::string& name, const std::string& stype) {
    if (name.size() > 0xFFFF || stype.size() > 0xFFFF)
      throw ColParseError("typedef name exceeds 65535 bytes");
    int32_t ntid = local_tid(name);
    int32_t stid = local_tid(stype);
    std::string name_hash(tid_hash(ntid), 32);
    std::string stype_hash(tid_hash(stid), 32);
    out.td_name_tid.push_back(ntid);
    out.td_stype_tid.push_back(stid);
    out.td_hex += c_composite_hash({mark_hash_, stype_hash, base_hash_});
    out.td_hex += c_composite_hash({mark_hash_, name_hash, stype_hash});
  }

  void emit_terminal(const std::string& name, const std::string& stype) {
    if (stype.size() > 0xFFFF)
      throw ColParseError("terminal type name exceeds 65535 bytes");
    out.term_tid.push_back(local_tid(stype));
    out.term_hex += terminal_hash(stype, name);
    out.name_blob += name;
    out.name_end.push_back(out.name_blob.size());
  }

  struct Elem {
    std::string hash;      // 32-hex
    std::string cthash;    // 32-hex: stype hash (terminal) or ct (sub-link)
  };
  struct Frame {
    bool has_head = false;
    std::string head;
    std::vector<Elem> elems;
  };

  // returns (hash_code, ct_hash) of the emitted link
  std::pair<std::string, std::string> emit_link(Frame& f, bool toplevel) {
    if (f.head.size() > 0xFFFF)
      throw ColParseError("link type name exceeds 65535 bytes");
    if (f.elems.size() > 0xFFFF)
      throw ColParseError("link arity exceeds 65535 elements");
    int32_t tid = local_tid(f.head);
    std::string head_hash(tid_hash(tid), 32);
    std::vector<std::string> parts;
    parts.reserve(f.elems.size() + 1);
    parts.push_back(head_hash);
    for (auto& e : f.elems) parts.push_back(e.cthash);
    std::string ct_hash = c_composite_hash(parts);
    parts.clear();
    parts.push_back(head_hash);
    for (auto& e : f.elems) parts.push_back(e.hash);
    std::string hash_code = c_composite_hash(parts);

    out.link_tid.push_back(tid);
    out.link_hex += ct_hash;
    out.link_hex += hash_code;
    out.link_top.push_back(toplevel ? 1 : 0);
    out.link_ne.push_back((uint32_t)f.elems.size());
    for (auto& e : f.elems) out.elem_hex += e.hash;
    return {std::move(hash_code), std::move(ct_hash)};
  }

  void parse_expression_line(const char* line, size_t n, long lineno,
                             const std::string& origin) {
    std::vector<Frame> frames;
    std::string token;
    bool result_emitted = false;
    size_t i = 0;

    auto close_token = [&]() {
      if (!token.empty()) {
        if (frames.empty() || frames.back().has_head)
          fail(origin, lineno, line, n, "unexpected symbol '" + token + "'");
        frames.back().head = token;
        frames.back().has_head = true;
        token.clear();
      }
    };

    while (i < n) {
      char c = line[i];
      if (c == '(') {
        close_token();
        frames.emplace_back();
      } else if (c == ')') {
        close_token();
        if (frames.empty()) fail(origin, lineno, line, n, "unbalanced ')'");
        Frame f = std::move(frames.back());
        frames.pop_back();
        if (!f.has_head) fail(origin, lineno, line, n, "headless expression");
        bool toplevel = frames.empty();
        auto hc = emit_link(f, toplevel);
        if (!frames.empty()) {
          frames.back().elems.push_back(
              Elem{std::move(hc.first), std::move(hc.second)});
        } else {
          result_emitted = true;
        }
      } else if (c == '"') {
        size_t j = i + 1;
        while (j < n && !(line[j] == '"' && line[j - 1] != '\\')) j++;
        if (j >= n) fail(origin, lineno, line, n, "unterminated string");
        std::string body(line + i + 1, j - i - 1);
        size_t sp = body.find(' ');
        if (sp == std::string::npos || frames.empty())
          fail(origin, lineno, line, n, "bad canonical terminal '" + body + "'");
        std::string stype = body.substr(0, sp);
        std::string name = body.substr(sp + 1);
        std::string stype_hash(tid_hash(local_tid(stype)), 32);
        frames.back().elems.push_back(
            Elem{terminal_hash(stype, name), std::move(stype_hash)});
        i = j;
      } else if (c == ' ') {
        close_token();
      } else {
        token.push_back(c);
      }
      i++;
    }
    if (!frames.empty() || !result_emitted)
      fail(origin, lineno, line, n, "unbalanced expression");
  }

  void process_line(const char* b, size_t n, long lineno,
                    const std::string& origin) {
    while (n && std::isspace((unsigned char)b[0])) { b++; n--; }
    while (n && std::isspace((unsigned char)b[n - 1])) n--;
    if (!n) return;
    // first whitespace-delimited token is exactly "(:" — the typedef /
    // terminal-declaration mark (split_ws only for those ~10% of lines)
    bool mark = n >= 2 && b[0] == '(' && b[1] == ':' &&
                (n == 2 || std::isspace((unsigned char)b[2]));
    if (mark) {
      std::string line(b, n);
      std::vector<std::string> parts = c_split_ws(line);
      if (parts.size() < 2) fail(origin, lineno, line, "bad typedef");
      if (parts[1][0] == '"') {
        note_class(2, origin, lineno, b, n);
        std::string joined;
        for (size_t k = 1; k + 1 < parts.size(); k++) {
          if (k > 1) joined.push_back(' ');
          joined += parts[k];
        }
        emit_terminal(c_strip_quotes(joined), c_rstrip_paren(parts.back()));
      } else {
        note_class(1, origin, lineno, b, n);
        if (parts.size() != 3) fail(origin, lineno, line, "bad typedef");
        emit_typedef(parts[1], c_rstrip_paren(parts.back()));
      }
      return;
    }
    note_class(3, origin, lineno, b, n);
    if (b[0] != '(' || b[n - 1] != ')')
      fail(origin, lineno, b, n, "bad expression line");
    parse_expression_line(b, n, lineno, origin);
  }
};

// -- dedup map --------------------------------------------------------------

// classes for the dedup/index map
enum : uint8_t { CLS_TD = 1, CLS_NODE = 2, CLS_LINK = 3 };

// Open addressing in struct-of-arrays layout: the probe loop touches only
// the 16-byte key array (one cache line per probe in the common case); the
// packed value array is read on hit.  The table is sized by RECORD count
// only — element lookups in pass 2 use find() and never insert, so the
// table stays ~4x smaller than a record+element sizing (1 GB vs 6 GB at
// the 27.9M-link reference scale: random probes into the smaller table
// were the difference between a ~123 s and a ~55 s merge on one core).
struct DedupMap {
  static constexpr uint32_t EMPTY = 0xFFFFFFFFu;

  std::vector<uint64_t> keys;  // 2 per slot: lo, hi
  std::vector<uint32_t> vals;  // idx (30 bits) | cls << 30
  uint64_t mask = 0;

  void init(size_t n_keys) {
    size_t cap = 64;
    while (cap < n_keys * 2) cap <<= 1;
    keys.assign(cap * 2, 0);
    vals.assign(cap, EMPTY);
    mask = cap - 1;
  }

  static void split(const uint8_t bin[16], uint64_t& lo, uint64_t& hi) {
    std::memcpy(&lo, bin, 8);
    std::memcpy(&hi, bin + 8, 8);
  }

  // returns slot index; caller checks vals[slot] and may claim it
  size_t find_slot(uint64_t lo, uint64_t hi) const {
    uint64_t i = lo & mask;
    for (;;) {
      if (vals[i] == EMPTY ||
          (keys[2 * i] == lo && keys[2 * i + 1] == hi))
        return (size_t)i;
      i = (i + 1) & mask;
    }
  }

  // insert-or-get: returns packed value, EMPTY if newly claimed
  uint32_t upsert(const uint8_t bin[16], uint32_t packed) {
    uint64_t lo, hi;
    split(bin, lo, hi);
    size_t i = find_slot(lo, hi);
    uint32_t cur = vals[i];
    if (cur == EMPTY) {
      keys[2 * i] = lo;
      keys[2 * i + 1] = hi;
      vals[i] = packed;
    }
    return cur;
  }

  // pure lookup (pass 2): never writes, table never grows
  uint32_t find(const uint8_t bin[16]) const {
    uint64_t lo, hi;
    split(bin, lo, hi);
    return vals[find_slot(lo, hi)];
  }

  static uint32_t pack(uint8_t cls, uint32_t idx) {
    return ((uint32_t)cls << 30) | idx;
  }
  static uint8_t cls_of(uint32_t v) { return (uint8_t)(v >> 30); }
  static uint32_t idx_of(uint32_t v) { return v & 0x3FFFFFFFu; }
};

// -- merged result ----------------------------------------------------------

struct ColResult {
  std::string error;

  std::string type_blob;
  std::vector<uint32_t> type_off;   // n_types+1
  std::vector<uint8_t> type_hash;   // 16*n_types

  std::vector<int32_t> td_name_tid, td_stype_tid;
  std::vector<uint8_t> td_ct, td_hash;  // 16 per record

  std::vector<uint8_t> node_hash;   // 16*n_nodes
  std::vector<int32_t> node_tid;
  std::string node_name_blob;
  std::vector<uint64_t> node_name_off;  // n_nodes+1

  std::vector<uint8_t> link_hash, link_ct;  // 16*n_links
  std::vector<int32_t> link_tid;
  std::vector<uint8_t> link_top;
  std::vector<uint64_t> link_elem_off;  // n_links+1
  std::vector<int32_t> link_elem;       // flat resolved indices

  std::string dangling_blob;  // 32-hex per dangling element hash
};

struct Chunk {
  const char* text;
  size_t len;
  std::string origin;
  long first_lineno;
  LocalCols cols;
};

void merge_chunks(std::vector<Chunk>& chunks, ColResult& res) {
  // seam validation: sections must be globally ordered, and expressions
  // need a preceding terminals section (the reference machine's TYPES
  // state rejects a bare expression file)
  uint8_t last_class = 0;
  bool seen_terminal = false;
  const std::string* cur_origin = nullptr;
  for (auto& c : chunks) {
    if (!c.cols.error.empty()) {
      res.error = c.cols.error;
      return;
    }
    if (cur_origin == nullptr || *cur_origin != c.origin) {
      // each FILE runs its own section machine (reference semantics)
      cur_origin = &c.origin;
      last_class = 0;
      seen_terminal = false;
    }
    if (c.cols.first_class && last_class && c.cols.first_class < last_class) {
      res.error = c.origin + ": out-of-order canonical section at chunk seam";
      return;
    }
    if (c.cols.expr_before_terminal && !seen_terminal) {
      res.error = c.origin + ": expected typedef/terminal before expressions";
      return;
    }
    if (c.cols.last_class) last_class = c.cols.last_class;
    if (c.cols.saw_terminal) seen_terminal = true;
  }

  // global type pool
  std::unordered_map<std::string, int32_t> gtid_of;
  std::vector<std::vector<int32_t>> remap(chunks.size());
  res.type_off.push_back(0);
  for (size_t ci = 0; ci < chunks.size(); ci++) {
    auto& lc = chunks[ci].cols;
    remap[ci].resize(lc.type_names.size());
    for (size_t t = 0; t < lc.type_names.size(); t++) {
      auto it = gtid_of.find(lc.type_names[t]);
      int32_t g;
      if (it == gtid_of.end()) {
        g = (int32_t)gtid_of.size();
        gtid_of.emplace(lc.type_names[t], g);
        res.type_blob += lc.type_names[t];
        res.type_off.push_back((uint32_t)res.type_blob.size());
        uint8_t bin[16];
        hex2bin(lc.type_hash_hex.data() + 32 * t, bin);
        res.type_hash.insert(res.type_hash.end(), bin, bin + 16);
      } else {
        g = it->second;
      }
      remap[ci][t] = g;
    }
  }

  // exact upper bounds from the chunk sums: reserve once, never realloc
  // (doubling growth at multi-GB sizes re-copies gigabytes)
  size_t n_td = 0, n_term = 0, n_link = 0, n_elem = 0, name_bytes = 0;
  for (auto& c : chunks) {
    n_td += c.cols.td_name_tid.size();
    n_term += c.cols.term_tid.size();
    n_link += c.cols.link_tid.size();
    n_elem += c.cols.elem_hex.size() / 32;
    name_bytes += c.cols.name_blob.size();
  }
  if (n_td + n_term + n_link >= 0x3FFFFFFFull) {
    // packed values carry a 30-bit index; 0xFFFFFFFF is the EMPTY
    // sentinel — fence the encoding instead of corrupting silently
    res.error = "columnar merge: > 2^30-1 records unsupported";
    return;
  }
  DedupMap map;
  map.init(n_td + n_term + n_link);
  res.td_name_tid.reserve(n_td);
  res.td_stype_tid.reserve(n_td);
  res.td_hash.reserve(n_td * 16);
  res.td_ct.reserve(n_td * 16);
  res.node_tid.reserve(n_term);
  res.node_hash.reserve(n_term * 16);
  res.node_name_blob.reserve(name_bytes);
  res.node_name_off.reserve(n_term + 1);
  res.link_tid.reserve(n_link);
  res.link_hash.reserve(n_link * 16);
  res.link_ct.reserve(n_link * 16);
  res.link_top.reserve(n_link);
  res.link_elem_off.reserve(n_link + 1);

  // pass 1: dedup + dense index assignment, (file, chunk) order.
  // elem hex blocks of RETAINED links are concatenated for pass 2.
  std::string kept_elem_hex;
  kept_elem_hex.reserve(n_elem * 32);
  res.link_elem_off.push_back(0);
  res.node_name_off.push_back(0);
  uint8_t bin[16];
  for (size_t ci = 0; ci < chunks.size(); ci++) {
    auto& lc = chunks[ci].cols;
    // typedefs
    for (size_t i = 0; i < lc.td_name_tid.size(); i++) {
      const char* hx = lc.td_hex.data() + 64 * i;
      hex2bin(hx + 32, bin);  // hash_code
      uint32_t cur = map.upsert(
          bin, DedupMap::pack(CLS_TD, (uint32_t)res.td_name_tid.size()));
      if (cur != DedupMap::EMPTY) continue;
      res.td_name_tid.push_back(remap[ci][lc.td_name_tid[i]]);
      res.td_stype_tid.push_back(remap[ci][lc.td_stype_tid[i]]);
      res.td_hash.insert(res.td_hash.end(), bin, bin + 16);
      hex2bin(hx, bin);
      res.td_ct.insert(res.td_ct.end(), bin, bin + 16);
    }
    // terminals
    uint64_t nstart = 0;
    for (size_t i = 0; i < lc.term_tid.size(); i++) {
      uint64_t nend = lc.name_end[i];
      hex2bin(lc.term_hex.data() + 32 * i, bin);
      uint32_t cur = map.upsert(
          bin, DedupMap::pack(CLS_NODE, (uint32_t)res.node_tid.size()));
      if (cur == DedupMap::EMPTY) {
        res.node_tid.push_back(remap[ci][lc.term_tid[i]]);
        res.node_hash.insert(res.node_hash.end(), bin, bin + 16);
        res.node_name_blob.append(lc.name_blob, nstart, nend - nstart);
        res.node_name_off.push_back(res.node_name_blob.size());
      }
      nstart = nend;
    }
    // links
    uint64_t estart = 0;
    for (size_t i = 0; i < lc.link_tid.size(); i++) {
      uint64_t ne = lc.link_ne[i];
      const char* hx = lc.link_hex.data() + 64 * i;
      hex2bin(hx + 32, bin);  // hash_code
      uint32_t cur = map.upsert(
          bin, DedupMap::pack(CLS_LINK, (uint32_t)res.link_tid.size()));
      if (cur != DedupMap::EMPTY) {
        if (DedupMap::cls_of(cur) == CLS_LINK && lc.link_top[i])
          res.link_top[DedupMap::idx_of(cur)] = 1;
      } else {
        res.link_tid.push_back(remap[ci][lc.link_tid[i]]);
        res.link_hash.insert(res.link_hash.end(), bin, bin + 16);
        hex2bin(hx, bin);
        res.link_ct.insert(res.link_ct.end(), bin, bin + 16);
        res.link_top.push_back(lc.link_top[i]);
        kept_elem_hex.append(lc.elem_hex, estart * 32, ne * 32);
        res.link_elem_off.push_back(res.link_elem_off.back() + ne);
      }
      estart += ne;
    }
    // chunk fully merged: release its buffers
    LocalCols freed;
    std::swap(lc, freed);
  }

  // pass 2: element resolution (node i -> i, link j -> n_nodes + j,
  // -1 dangling) — pure lookups, the table never grows
  const int32_t n_nodes = (int32_t)res.node_tid.size();
  size_t n_kept = kept_elem_hex.size() / 32;
  res.link_elem.resize(n_kept);
  for (size_t e = 0; e < n_kept; e++) {
    hex2bin(kept_elem_hex.data() + 32 * e, bin);
    uint32_t v = map.find(bin);
    if (v != DedupMap::EMPTY && DedupMap::cls_of(v) == CLS_NODE) {
      res.link_elem[e] = (int32_t)DedupMap::idx_of(v);
    } else if (v != DedupMap::EMPTY && DedupMap::cls_of(v) == CLS_LINK) {
      res.link_elem[e] = n_nodes + (int32_t)DedupMap::idx_of(v);
    } else {
      res.link_elem[e] = -1;
      res.dangling_blob.append(kept_elem_hex, 32 * e, 32);
    }
  }
}

}  // namespace

extern "C" {

void* das_parse_files_columnar(const char** paths, int n, int n_threads) {
  auto* res = new ColResult();
  // read files up front; chunk at newline boundaries
  std::vector<std::unique_ptr<std::string>> file_data;
  std::vector<Chunk> chunks;
  const size_t target = 16u << 20;  // 16 MB chunks
  for (int f = 0; f < n; f++) {
    std::ifstream in(paths[f], std::ios::binary | std::ios::ate);
    if (!in) {
      res->error = std::string("cannot open ") + paths[f];
      return res;
    }
    auto sz = (size_t)in.tellg();
    in.seekg(0);
    auto data = std::make_unique<std::string>();
    data->resize(sz);
    if (sz) in.read(&(*data)[0], (std::streamsize)sz);
    const char* base = data->data();
    size_t pos = 0;
    long lineno = 1;
    while (pos < sz) {
      size_t end = pos + target < sz ? pos + target : sz;
      while (end < sz && base[end] != '\n') end++;
      if (end < sz) end++;  // include the newline
      Chunk c;
      c.text = base + pos;
      c.len = end - pos;
      c.origin = paths[f];
      c.first_lineno = lineno;
      for (size_t k = pos; k < end; k++)
        if (base[k] == '\n') lineno++;
      chunks.push_back(std::move(c));
      pos = end;
    }
    file_data.push_back(std::move(data));
  }

  const bool verbose = std::getenv("DAS_COL_VERBOSE") != nullptr;
  auto t0 = std::chrono::steady_clock::now();
  auto lap = [&](const char* what) {
    if (!verbose) return;
    auto t1 = std::chrono::steady_clock::now();
    std::fprintf(stderr, "[das_columnar] %s: %.1fs\n", what,
                 std::chrono::duration<double>(t1 - t0).count());
    t0 = t1;
  };
  int workers = n_threads > 0 ? n_threads : 1;
  if (workers > (int)chunks.size()) workers = (int)chunks.size();
  std::atomic<size_t> next{0};
  auto work = [&]() {
    for (;;) {
      size_t i = next.fetch_add(1);
      if (i >= chunks.size()) return;
      try {
        ChunkScanner s;
        s.parse(chunks[i].text, chunks[i].len, chunks[i].origin,
                chunks[i].first_lineno);
        chunks[i].cols = std::move(s.out);
      } catch (const ColParseError& e) {
        chunks[i].cols.error = e.msg;
      } catch (const std::exception& e) {
        chunks[i].cols.error = chunks[i].origin + ": " + e.what();
      }
    }
  };
  if (workers <= 1) {
    work();
  } else {
    std::vector<std::thread> ts;
    for (int w = 0; w < workers; w++) ts.emplace_back(work);
    for (auto& t : ts) t.join();
  }

  lap("parse");
  try {
    merge_chunks(chunks, *res);
  } catch (const std::exception& e) {
    res->error = std::string("columnar merge: ") + e.what();
  }
  lap("merge");
  return res;
}

const char* das_col_error(void* h) {
  return static_cast<ColResult*>(h)->error.c_str();
}

// field ids — keep in sync with das_tpu/ingest/native.py
//  0 type_off u32   1 type_blob    2 type_hash u8x16
//  3 td_name_tid    4 td_stype_tid 5 td_ct      6 td_hash
//  7 node_hash      8 node_tid     9 node_name_off u64  10 node_name_blob
// 11 link_hash     12 link_tid    13 link_ct   14 link_top
// 15 link_elem_off 16 link_elem   17 dangling_blob
int das_col_get(void* h, int field, const uint8_t** ptr, uint64_t* nbytes) {
  auto* r = static_cast<ColResult*>(h);
  auto set = [&](const void* p, size_t nb) {
    *ptr = static_cast<const uint8_t*>(p);
    *nbytes = nb;
    return 0;
  };
  switch (field) {
    case 0: return set(r->type_off.data(), r->type_off.size() * 4);
    case 1: return set(r->type_blob.data(), r->type_blob.size());
    case 2: return set(r->type_hash.data(), r->type_hash.size());
    case 3: return set(r->td_name_tid.data(), r->td_name_tid.size() * 4);
    case 4: return set(r->td_stype_tid.data(), r->td_stype_tid.size() * 4);
    case 5: return set(r->td_ct.data(), r->td_ct.size());
    case 6: return set(r->td_hash.data(), r->td_hash.size());
    case 7: return set(r->node_hash.data(), r->node_hash.size());
    case 8: return set(r->node_tid.data(), r->node_tid.size() * 4);
    case 9: return set(r->node_name_off.data(), r->node_name_off.size() * 8);
    case 10: return set(r->node_name_blob.data(), r->node_name_blob.size());
    case 11: return set(r->link_hash.data(), r->link_hash.size());
    case 12: return set(r->link_tid.data(), r->link_tid.size() * 4);
    case 13: return set(r->link_ct.data(), r->link_ct.size());
    case 14: return set(r->link_top.data(), r->link_top.size());
    case 15: return set(r->link_elem_off.data(), r->link_elem_off.size() * 8);
    case 16: return set(r->link_elem.data(), r->link_elem.size() * 4);
    case 17: return set(r->dangling_blob.data(), r->dangling_blob.size());
    default: return -1;
  }
}

void das_col_free(void* h) { delete static_cast<ColResult*>(h); }

}  // extern "C"
