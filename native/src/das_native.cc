// Native canonical knowledge-base scanner.
//
// C++ implementation of the canonical fast-path loader
// (das_tpu/ingest/canonical.py; role of the reference's
// /root/reference/das/canonical_parser.py:242-365): a three-state line
// scanner (types -> terminals -> expressions) plus a char-level expression
// parser that computes all md5 handles inline.  Files are parsed on
// std::thread workers (one scanner per file — the canonical state machine is
// per-file), each producing a flat little-endian record stream the Python
// side decodes into AtomSpaceData (das_tpu/ingest/native.py).
//
// Record stream format (little-endian):
//   tag u8: 1=typedef  2=terminal  3=link
//   typedef : u16 name_len, name | u16 stype_len, stype
//             | 32B name_hash | 32B stype_hash | 32B ct_hash | 32B hash_code
//   terminal: u16 stype_len, stype | u32 name_len, name
//             | 32B stype_hash | 32B terminal_hash
//   link    : u16 type_len, named_type | u8 toplevel | u16 n_elements
//             | n_elements x u8 kind (0=sub-expression, 1=terminal)
//             | one contiguous hex block (single-decode friendly):
//               32B named_type_hash | n_elements x 32B element_hash
//               | (per kind==1 element, in order) 32B stype_hash
//               | 32B ct_hash | 32B hash_code
//
// All hashes are 32-char lowercase hex (md5), identical to the Python path.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <memory>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "md5.h"

namespace {

// ---------------------------------------------------------------------------
// record buffer
// ---------------------------------------------------------------------------

struct Buf {
  std::vector<uint8_t> v;

  void u8(uint8_t x) { v.push_back(x); }
  void u16(uint16_t x) {
    v.push_back((uint8_t)x);
    v.push_back((uint8_t)(x >> 8));
  }
  void u32(uint32_t x) {
    for (int i = 0; i < 4; i++) v.push_back((uint8_t)(x >> (8 * i)));
  }
  void bytes(const std::string& s) {
    v.insert(v.end(), s.begin(), s.end());
  }
  void str16(const std::string& s) {
    u16((uint16_t)s.size());
    bytes(s);
  }
  void str32(const std::string& s) {
    u32((uint32_t)s.size());
    bytes(s);
  }
  void hex(const std::string& h) { bytes(h); }  // always 32 chars
};

struct ParseError {
  std::string msg;
  explicit ParseError(std::string m) : msg(std::move(m)) {}
};

// ---------------------------------------------------------------------------
// hashing (parity with das_tpu/core/hashing.py)
// ---------------------------------------------------------------------------

std::string composite_hash(const std::vector<std::string>& parts) {
  if (parts.size() == 1) return parts[0];  // singleton collapse
  std::string joined;
  size_t total = parts.size() - 1;
  for (const auto& p : parts) total += p.size();
  joined.reserve(total);
  for (size_t i = 0; i < parts.size(); i++) {
    if (i) joined.push_back(' ');
    joined += parts[i];
  }
  return md5_hex_str(joined);
}

// ---------------------------------------------------------------------------
// the scanner (mirrors das_tpu/ingest/canonical.py CanonicalLoader)
// ---------------------------------------------------------------------------

struct Elem {
  uint8_t kind;  // 0 = sub-expression, 1 = terminal
  std::string hash;
  std::string stype_hash;  // kind==1 only
};

struct Frame {
  bool has_head = false;
  std::string head;
  std::vector<Elem> elems;
  std::vector<std::string> cthashes;
};

class Scanner {
 public:
  Scanner() {
    mark_hash_ = md5_hex_str(":");
    base_hash_ = md5_hex_str("Type");
  }

  Buf buf;

  void parse_stream(std::istream& in, const std::string& origin) {
    std::string line;
    long lineno = 0;
    while (std::getline(in, line)) {
      lineno++;
      process_line(line, lineno, origin);
    }
  }

  void parse_text(const char* text, size_t len, const std::string& origin) {
    long lineno = 0;
    size_t pos = 0;
    while (pos <= len) {
      size_t nl = pos;
      while (nl < len && text[nl] != '\n') nl++;
      lineno++;
      std::string line(text + pos, nl - pos);
      process_line(line, lineno, origin);
      if (nl >= len) break;
      pos = nl + 1;
    }
  }

 private:
  enum State { TYPES, TERMINALS, EXPRESSIONS };
  State state_ = TYPES;
  std::string mark_hash_, base_hash_;
  std::unordered_map<std::string, std::string> type_hash_;

  const std::string& named_hash(const std::string& name) {
    auto it = type_hash_.find(name);
    if (it != type_hash_.end()) return it->second;
    return type_hash_.emplace(name, md5_hex_str(name)).first->second;
  }

  static std::string terminal_hash(const std::string& type, const std::string& name) {
    std::string s;
    s.reserve(type.size() + 1 + name.size());
    s += type;
    s.push_back(' ');
    s += name;
    return md5_hex_str(s);
  }

  [[noreturn]] static void fail(const std::string& origin, long lineno,
                                const std::string& line, const std::string& reason) {
    throw ParseError(origin + ": line " + std::to_string(lineno) + ": " + reason +
                     ": " + line);
  }

  // Python str.strip(): all leading/trailing whitespace.
  static std::string strip(const std::string& s) {
    size_t a = 0, b = s.size();
    while (a < b && std::isspace((unsigned char)s[a])) a++;
    while (b > a && std::isspace((unsigned char)s[b - 1])) b--;
    return s.substr(a, b - a);
  }

  // Python str.split(): tokens separated by whitespace runs.
  static std::vector<std::string> split_ws(const std::string& s) {
    std::vector<std::string> out;
    size_t i = 0, n = s.size();
    while (i < n) {
      while (i < n && std::isspace((unsigned char)s[i])) i++;
      size_t j = i;
      while (j < n && !std::isspace((unsigned char)s[j])) j++;
      if (j > i) out.push_back(s.substr(i, j - i));
      i = j;
    }
    return out;
  }

  // Python str.rstrip(")") — strip ALL trailing ')'.
  static std::string rstrip_paren(const std::string& s) {
    size_t b = s.size();
    while (b > 0 && s[b - 1] == ')') b--;
    return s.substr(0, b);
  }

  // Python str.strip('"') — strip ALL leading/trailing '"'.
  static std::string strip_quotes(const std::string& s) {
    size_t a = 0, b = s.size();
    while (a < b && s[a] == '"') a++;
    while (b > a && s[b - 1] == '"') b--;
    return s.substr(a, b - a);
  }

  void emit_typedef(const std::string& name, const std::string& stype) {
    if (name.size() > 0xFFFF || stype.size() > 0xFFFF)
      throw ParseError("typedef name exceeds 65535 bytes");
    const std::string name_hash = named_hash(name);
    const std::string stype_hash = named_hash(stype);
    const std::string ct_hash =
        composite_hash({mark_hash_, stype_hash, base_hash_});
    const std::string hash_code =
        composite_hash({mark_hash_, name_hash, stype_hash});
    buf.u8(1);
    buf.str16(name);
    buf.str16(stype);
    buf.hex(name_hash);
    buf.hex(stype_hash);
    buf.hex(ct_hash);
    buf.hex(hash_code);
  }

  void emit_terminal(const std::string& name, const std::string& stype) {
    if (stype.size() > 0xFFFF)
      throw ParseError("terminal type name exceeds 65535 bytes");
    const std::string stype_hash = named_hash(stype);
    buf.u8(2);
    buf.str16(stype);
    buf.str32(name);
    buf.hex(stype_hash);
    buf.hex(terminal_hash(stype, name));
  }

  // Emits one link record; returns (hash_code, ct_hash).
  std::pair<std::string, std::string> emit_link(Frame& f, bool toplevel) {
    const std::string& head_hash = named_hash(f.head);
    std::vector<std::string> ct_parts;
    ct_parts.reserve(f.cthashes.size() + 1);
    ct_parts.push_back(head_hash);
    for (auto& h : f.cthashes) ct_parts.push_back(h);
    std::string ct_hash = composite_hash(ct_parts);
    std::vector<std::string> h_parts;
    h_parts.reserve(f.elems.size() + 1);
    h_parts.push_back(head_hash);
    for (auto& e : f.elems) h_parts.push_back(e.hash);
    std::string hash_code = composite_hash(h_parts);

    if (f.head.size() > 0xFFFF)
      throw ParseError("link type name exceeds 65535 bytes");
    if (f.elems.size() > 0xFFFF)
      throw ParseError("link arity exceeds 65535 elements");
    buf.u8(3);
    buf.str16(f.head);
    buf.u8(toplevel ? 1 : 0);
    buf.u16((uint16_t)f.elems.size());
    for (auto& e : f.elems) buf.u8(e.kind);
    buf.hex(head_hash);
    for (auto& e : f.elems) buf.hex(e.hash);
    for (auto& e : f.elems)
      if (e.kind == 1) buf.hex(e.stype_hash);
    buf.hex(ct_hash);
    buf.hex(hash_code);
    return {std::move(hash_code), std::move(ct_hash)};
  }

  void parse_expression_line(const std::string& line, long lineno,
                             const std::string& origin) {
    std::vector<Frame> frames;
    std::string token;
    bool result_emitted = false;
    size_t i = 0, n = line.size();

    auto close_token = [&]() {
      if (!token.empty()) {
        if (frames.empty() || frames.back().has_head)
          fail(origin, lineno, line, "unexpected symbol '" + token + "'");
        frames.back().head = token;
        frames.back().has_head = true;
        token.clear();
      }
    };

    while (i < n) {
      char c = line[i];
      if (c == '(') {
        close_token();
        frames.emplace_back();
      } else if (c == ')') {
        close_token();
        if (frames.empty()) fail(origin, lineno, line, "unbalanced ')'");
        Frame f = std::move(frames.back());
        frames.pop_back();
        if (!f.has_head) fail(origin, lineno, line, "headless expression");
        bool toplevel = frames.empty();
        auto hc = emit_link(f, toplevel);
        if (!frames.empty()) {
          frames.back().elems.push_back(Elem{0, std::move(hc.first), {}});
          frames.back().cthashes.push_back(std::move(hc.second));
        } else {
          result_emitted = true;
        }
      } else if (c == '"') {
        size_t j = i + 1;
        while (j < n && !(line[j] == '"' && line[j - 1] != '\\')) j++;
        if (j >= n) fail(origin, lineno, line, "unterminated string");
        std::string body = line.substr(i + 1, j - i - 1);
        size_t sp = body.find(' ');
        if (sp == std::string::npos || frames.empty())
          fail(origin, lineno, line, "bad canonical terminal '" + body + "'");
        std::string stype = body.substr(0, sp);
        std::string name = body.substr(sp + 1);
        const std::string& stype_hash = named_hash(stype);
        frames.back().elems.push_back(
            Elem{1, terminal_hash(stype, name), stype_hash});
        frames.back().cthashes.push_back(stype_hash);
        i = j;
      } else if (c == ' ') {
        close_token();
      } else {
        token.push_back(c);
      }
      i++;
    }
    if (!frames.empty() || !result_emitted)
      fail(origin, lineno, line, "unbalanced expression");
  }

  void process_line(const std::string& raw, long lineno, const std::string& origin) {
    std::string line = strip(raw);
    if (line.empty()) return;
    std::vector<std::string> parts = split_ws(line);
    if (state_ == TYPES) {
      if (parts[0] != "(:")
        fail(origin, lineno, line, "expected typedef");
      if (parts.size() < 2) fail(origin, lineno, line, "bad typedef");
      if (parts[1][0] == '"') {
        state_ = TERMINALS;
      } else {
        if (parts.size() != 3) fail(origin, lineno, line, "bad typedef");
        emit_typedef(parts[1], rstrip_paren(parts.back()));
        return;
      }
    }
    if (state_ == TERMINALS) {
      if (parts[0] == "(:") {
        // name = " ".join(parts[1:-1]).strip('"')
        std::string joined;
        for (size_t k = 1; k + 1 < parts.size(); k++) {
          if (k > 1) joined.push_back(' ');
          joined += parts[k];
        }
        emit_terminal(strip_quotes(joined), rstrip_paren(parts.back()));
        return;
      }
      state_ = EXPRESSIONS;
    }
    // EXPRESSIONS
    if (parts[0] == "(:" || line.front() != '(' || line.back() != ')')
      fail(origin, lineno, line, "bad expression line");
    parse_expression_line(line, lineno, origin);
  }
};

// ---------------------------------------------------------------------------
// results + threading
// ---------------------------------------------------------------------------

struct Result {
  std::vector<Buf> buffers;  // one per input, in input order
  std::string error;
};

}  // namespace

extern "C" {

// Parse canonical files on up to n_threads workers.  Returns an opaque
// Result*; check das_error() before reading buffers.
void* das_parse_files(const char** paths, int n, int n_threads) {
  auto* res = new Result();
  res->buffers.resize(n);
  std::vector<std::string> errors(n);
  std::atomic<int> next{0};
  int workers = n_threads > 0 ? n_threads : 1;
  if (workers > n) workers = n;
  auto work = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) return;
      try {
        std::ifstream in(paths[i], std::ios::binary);
        if (!in) throw ParseError(std::string("cannot open ") + paths[i]);
        Scanner s;
        s.parse_stream(in, paths[i]);
        res->buffers[i] = std::move(s.buf);
      } catch (const ParseError& e) {
        errors[i] = e.msg;
      } catch (const std::exception& e) {
        errors[i] = std::string(paths[i]) + ": " + e.what();
      }
    }
  };
  if (workers <= 1) {
    work();
  } else {
    std::vector<std::thread> ts;
    for (int w = 0; w < workers; w++) ts.emplace_back(work);
    for (auto& t : ts) t.join();
  }
  for (auto& e : errors) {
    if (!e.empty()) {
      res->error = e;
      break;
    }
  }
  return res;
}

void* das_parse_text(const char* text, uint64_t len) {
  auto* res = new Result();
  res->buffers.resize(1);
  try {
    Scanner s;
    s.parse_text(text, (size_t)len, "<text>");
    res->buffers[0] = std::move(s.buf);
  } catch (const ParseError& e) {
    res->error = e.msg;
  } catch (const std::exception& e) {
    res->error = std::string("<text>: ") + e.what();
  }
  return res;
}

// Frees one buffer's memory early (progressive decode of large loads).
void das_buffer_release(void* h, int i) {
  auto* res = static_cast<Result*>(h);
  if (i >= 0 && i < (int)res->buffers.size()) {
    Buf empty;
    std::swap(res->buffers[i], empty);
  }
}

int das_buffer_count(void* h) {
  return (int)static_cast<Result*>(h)->buffers.size();
}

const uint8_t* das_buffer(void* h, int i, uint64_t* size) {
  auto* res = static_cast<Result*>(h);
  if (i < 0 || i >= (int)res->buffers.size()) {
    *size = 0;
    return nullptr;
  }
  *size = res->buffers[i].v.size();
  return res->buffers[i].v.data();
}

const char* das_error(void* h) { return static_cast<Result*>(h)->error.c_str(); }

void das_free(void* h) { delete static_cast<Result*>(h); }

// Standalone md5 (for parity tests from Python).
void das_md5_hex(const char* data, uint64_t len, char out[32]) {
  md5_hex(data, (size_t)len, out);
}

}  // extern "C"
