#include "md5.h"

#include <cstring>

namespace {

const uint32_t S[64] = {
    7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22,
    5, 9,  14, 20, 5, 9,  14, 20, 5, 9,  14, 20, 5, 9,  14, 20,
    4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23,
    6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21};

// K[i] = floor(2^32 * |sin(i+1)|)
const uint32_t K[64] = {
    0xd76aa478, 0xe8c7b756, 0x242070db, 0xc1bdceee, 0xf57c0faf, 0x4787c62a,
    0xa8304613, 0xfd469501, 0x698098d8, 0x8b44f7af, 0xffff5bb1, 0x895cd7be,
    0x6b901122, 0xfd987193, 0xa679438e, 0x49b40821, 0xf61e2562, 0xc040b340,
    0x265e5a51, 0xe9b6c7aa, 0xd62f105d, 0x02441453, 0xd8a1e681, 0xe7d3fbc8,
    0x21e1cde6, 0xc33707d6, 0xf4d50d87, 0x455a14ed, 0xa9e3e905, 0xfcefa3f8,
    0x676f02d9, 0x8d2a4c8a, 0xfffa3942, 0x8771f681, 0x6d9d6122, 0xfde5380c,
    0xa4beea44, 0x4bdecfa9, 0xf6bb4b60, 0xbebfbc70, 0x289b7ec6, 0xeaa127fa,
    0xd4ef3085, 0x04881d05, 0xd9d4d039, 0xe6db99e5, 0x1fa27cf8, 0xc4ac5665,
    0xf4292244, 0x432aff97, 0xab9423a7, 0xfc93a039, 0x655b59c3, 0x8f0ccc92,
    0xffeff47d, 0x85845dd1, 0x6fa87e4f, 0xfe2ce6e0, 0xa3014314, 0x4e0811a1,
    0xf7537e82, 0xbd3af235, 0x2ad7d2bb, 0xeb86d391};

inline uint32_t rotl(uint32_t x, uint32_t c) { return (x << c) | (x >> (32 - c)); }

void process_block(uint32_t st[4], const uint8_t* p) {
  uint32_t M[16];
  for (int i = 0; i < 16; i++) {
    M[i] = (uint32_t)p[4 * i] | ((uint32_t)p[4 * i + 1] << 8) |
           ((uint32_t)p[4 * i + 2] << 16) | ((uint32_t)p[4 * i + 3] << 24);
  }
  uint32_t A = st[0], B = st[1], C = st[2], D = st[3];
  for (int i = 0; i < 64; i++) {
    uint32_t F;
    int g;
    if (i < 16) {
      F = (B & C) | (~B & D);
      g = i;
    } else if (i < 32) {
      F = (D & B) | (~D & C);
      g = (5 * i + 1) & 15;
    } else if (i < 48) {
      F = B ^ C ^ D;
      g = (3 * i + 5) & 15;
    } else {
      F = C ^ (B | ~D);
      g = (7 * i) & 15;
    }
    F = F + A + K[i] + M[g];
    A = D;
    D = C;
    C = B;
    B = B + rotl(F, S[i]);
  }
  st[0] += A;
  st[1] += B;
  st[2] += C;
  st[3] += D;
}

}  // namespace

void md5_hex(const char* data, size_t len, char out[32]) {
  uint32_t st[4] = {0x67452301, 0xefcdab89, 0x98badcfe, 0x10325476};
  size_t i = 0;
  for (; i + 64 <= len; i += 64) process_block(st, (const uint8_t*)data + i);
  uint8_t tail[128];
  size_t rem = len - i;
  if (rem) memcpy(tail, data + i, rem);
  tail[rem] = 0x80;
  size_t padded = (rem + 1 + 8 <= 64) ? 64 : 128;
  memset(tail + rem + 1, 0, padded - rem - 1 - 8);
  uint64_t bits = (uint64_t)len * 8;
  for (int b = 0; b < 8; b++) tail[padded - 8 + b] = (uint8_t)(bits >> (8 * b));
  process_block(st, tail);
  if (padded == 128) process_block(st, tail + 64);
  static const char* hexd = "0123456789abcdef";
  for (int w = 0; w < 4; w++) {
    for (int b = 0; b < 4; b++) {
      uint8_t byte = (uint8_t)(st[w] >> (8 * b));
      out[8 * w + 2 * b] = hexd[byte >> 4];
      out[8 * w + 2 * b + 1] = hexd[byte & 0xf];
    }
  }
}
